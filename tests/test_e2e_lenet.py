"""End-to-end ladder config 1: LeNet MNIST dygraph + compiled engine
(ref test style: python/paddle/fluid/tests/book/test_recognize_digits.py —
train to a loss threshold)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.engine import Engine


def _loader(n_batches=20, bs=64):
    ds = paddle.vision.datasets.MNIST(mode="train")
    return paddle.io.DataLoader(ds, batch_size=bs, shuffle=True,
                                drop_last=True)


def test_lenet_eager_loss_decreases():
    paddle.seed(0)
    model = paddle.vision.models.LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    losses = []
    for i, (x, y) in enumerate(_loader()):
        out = model(x)
        loss = loss_fn(out, y.squeeze(-1))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
        if i >= 15:
            break
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_lenet_engine_matches_and_learns():
    paddle.seed(0)
    model = paddle.vision.models.LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    eng = Engine(model, opt, lambda out, y: loss_fn(out, y.squeeze(-1)))
    losses = []
    for i, (x, y) in enumerate(_loader()):
        loss = eng.train_batch([x], [y])
        losses.append(float(loss.item()))
        if i >= 25:
            break
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
    # sync back and run eager eval
    eng.sync_to_layer()
    model.eval()
    ds = paddle.vision.datasets.MNIST(mode="test")
    x, y = paddle.io.default_collate_fn([ds[i] for i in range(128)])
    pred = model(x).numpy().argmax(-1)
    acc = (pred == y.numpy().squeeze(-1)).mean()
    assert acc > 0.15  # synthetic data: above chance


def test_hapi_model_fit():
    paddle.seed(0)
    model = paddle.Model(paddle.vision.models.LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    model.prepare(opt, lambda out, y: loss_fn(out, y.squeeze(-1)),
                  paddle.metric.Accuracy())
    train = paddle.vision.datasets.MNIST(mode="train")
    model.fit(train, batch_size=64, epochs=1, num_iters=10, verbose=0)
    res = model.evaluate(paddle.vision.datasets.MNIST(mode="test"),
                         batch_size=64, verbose=0)
    assert "eval_loss" in res and "eval_acc" in res


def test_model_save_load(tmp_path):
    model = paddle.Model(paddle.vision.models.LeNet())
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    model.prepare(opt, lambda out, y: loss_fn(out, y.squeeze(-1)))
    path = str(tmp_path / "lenet")
    model.save(path)
    model2 = paddle.Model(paddle.vision.models.LeNet())
    opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
    model2.prepare(opt2, lambda out, y: loss_fn(out, y.squeeze(-1)))
    model2.load(path)
    w1 = model.network.state_dict()["features.0.weight"].numpy()
    w2 = model2.network.state_dict()["features.0.weight"].numpy()
    np.testing.assert_allclose(w1, w2)
