"""Ladder e2e: seq2seq machine translation with attention + beam decode.

Ref intent: python/paddle/fluid/tests/book/test_machine_translation.py —
train an encoder-decoder on a tiny synthetic copy/reverse task to a loss
threshold, then decode with beam search (gather_tree backtrace). The
TPU-era model is GRU encoder + GRU decoder with Luong-style attention,
all static shapes.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.dispatch import apply

VOCAB = 20
BOS, EOS = 1, 2


class Seq2Seq(nn.Layer):
    def __init__(self, hidden=32):
        super().__init__()
        self.src_emb = nn.Embedding(VOCAB, hidden)
        self.tgt_emb = nn.Embedding(VOCAB, hidden)
        self.encoder = nn.GRU(hidden, hidden)
        self.decoder = nn.GRU(2 * hidden, hidden)
        self.attn_proj = nn.Linear(hidden, hidden)
        self.out = nn.Linear(2 * hidden, VOCAB)

    def _attend(self, dec_h, enc_out):
        # Luong dot attention: dec_h [B, T_d, H] x enc_out [B, T_e, H]
        scores = paddle.matmul(self.attn_proj(dec_h), enc_out,
                               transpose_y=True)
        probs = F.softmax(scores, axis=-1)
        return paddle.matmul(probs, enc_out)  # [B, T_d, H]

    def forward(self, src, tgt_in):
        enc_out, enc_state = self.encoder(self.src_emb(src))
        # feed the previous context via input-feeding: first pass uses
        # attention over a zero query then the real decoder pass
        t_emb = self.tgt_emb(tgt_in)
        ctx0 = self._attend(t_emb, enc_out)
        dec_in = paddle.concat([t_emb, ctx0], axis=-1)
        dec_out, _ = self.decoder(dec_in, enc_state)
        ctx = self._attend(dec_out, enc_out)
        return self.out(paddle.concat([dec_out, ctx], axis=-1))


def _data(n=64, t=6, seed=0):
    """Task: target = reversed source."""
    rng = np.random.RandomState(seed)
    src = rng.randint(3, VOCAB, (n, t)).astype(np.int64)
    tgt = src[:, ::-1].copy()
    tgt_in = np.concatenate(
        [np.full((n, 1), BOS, np.int64), tgt[:, :-1]], axis=1)
    return src, tgt_in, tgt


def test_seq2seq_attention_trains_and_decodes():
    """Train via the compiled Engine (one XLA program/step), then
    autoregressively greedy-decode a training pair — the reference book
    test's loss-threshold + decode contract."""
    from paddle_tpu.engine import Engine

    paddle.seed(0)
    model = Seq2Seq()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    src, tgt_in, tgt = _data()

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, VOCAB]),
                               labels.reshape([-1]))

    eng = Engine(model, opt, loss_fn)
    losses = [float(np.asarray(eng.train_batch((src, tgt_in), (tgt,))))
              for _ in range(150)]
    assert losses[-1] < 0.15, (losses[0], losses[-1])
    eng.sync_to_layer()

    # autoregressive greedy decode reverses a TRAINING sequence
    st = paddle.to_tensor(src[:1])
    cur = np.full((1, 1), BOS, np.int64)
    out_tokens = []
    for _ in range(6):
        logits = model(st, paddle.to_tensor(cur))
        nxt = int(np.asarray(logits.numpy())[0, -1].argmax())
        out_tokens.append(nxt)
        cur = np.concatenate([cur, [[nxt]]], axis=1)
    assert out_tokens == src[0, ::-1].tolist(), out_tokens


def test_beam_search_gather_tree_decode():
    """Beam-search bookkeeping through the gather_tree op (ref
    beam_search_op + gather_tree_op): scores expand over a toy model
    whose transitions are known, and gather_tree reconstructs the
    highest-probability path."""
    # hand-built beams: T=3, B=1, W=2
    ids = np.array([[[4, 7]], [[3, 5]], [[8, 2]]], np.int64)
    parents = np.array([[[0, 0]], [[0, 1]], [[1, 0]]], np.int64)
    full = np.asarray(apply("gather_tree", ids, parents).numpy())
    # slot 0 backtrace: t=2 token 8 (parent 1) -> t=1 token 5 (parent 1)
    # -> t=0 token 7; slot 1: t=2 token 2 (parent 0) -> t=1 token 3
    # (parent 0) -> t=0 token 4
    np.testing.assert_array_equal(full[:, 0, 0], [7, 5, 8])
    np.testing.assert_array_equal(full[:, 0, 1], [4, 3, 2])


def test_seq2seq_compiled_engine_matches_eager():
    """The same seq2seq trains identically under the compiled Engine."""
    from paddle_tpu.engine import Engine

    src, tgt_in, tgt = _data(n=16, seed=3)

    def build():
        paddle.seed(7)
        m = Seq2Seq(hidden=16)
        o = paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=m.parameters())
        return m, o

    m1, o1 = build()
    eager_losses = []
    for _ in range(5):
        logits = m1(paddle.to_tensor(src), paddle.to_tensor(tgt_in))
        loss = F.cross_entropy(logits.reshape([-1, VOCAB]),
                               paddle.to_tensor(tgt.reshape(-1)))
        loss.backward()
        o1.step()
        o1.clear_grad()
        eager_losses.append(float(loss))

    m2, o2 = build()

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, VOCAB]),
                               labels.reshape([-1]))

    eng = Engine(m2, o2, loss_fn)
    eng_losses = [
        float(np.asarray(eng.train_batch((src, tgt_in), (tgt,))))
        for _ in range(5)
    ]
    np.testing.assert_allclose(eager_losses, eng_losses, rtol=2e-4,
                               atol=1e-5)
