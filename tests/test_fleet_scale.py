"""Elastic serving fleet (ISSUE 12): open-loop workload generator,
dynamic ReplicaSet membership (add / drain-then-evict), the SLO-aware
Autoscaler, and the scale-event chaos sites.

Ref parity: the reference's ElasticManager treats elasticity as a
first-class robustness property on the training side; this file
certifies the serving-side counterpart — membership changes must never
lose or duplicate a request, newcomers must compile exactly once, and
the Router must never route to a replica that is `starting` or
`draining`.

The elastic-fleet tests share one module-scoped Router and run as a
lifecycle story in definition order (tier-1 disables random ordering):
probe routing invariants, roll back a faulted scale-up, grow under
load, drain with chaos at the drain sites, kill a draining replica
mid-flight, and finally refuse to remove the last healthy replica.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observe, serving
from paddle_tpu.framework import faults, monitor
from paddle_tpu.framework.flags import flag, get_flags, set_flags
from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining
from paddle_tpu.serving import Autoscaler, Router, Scenario, ServingMetrics
from paddle_tpu.serving import workload
from paddle_tpu.serving.fleet import REPLICA_STATE_CODES

REPO = Path(__file__).resolve().parent.parent
VOCAB = 97


def _wait(cond, timeout=30.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return cond()


# ---------------------------------------------------------------------------
# open-loop workload generator
# ---------------------------------------------------------------------------


def test_trace_deterministic_and_json_roundtrip(tmp_path):
    """Same seed -> bitwise-identical trace; the JSON spec replays it."""
    s1 = Scenario.swing(low_rps=4, high_rps=40, low_s=0.5, high_s=0.5,
                        seed=3, vocab=31)
    t1, t2 = s1.trace(), s1.trace()
    assert len(t1) == len(t2) > 0
    for a, b in zip(t1, t2):
        assert (a.t, a.user, a.max_new, a.priority) == \
            (b.t, b.user, b.max_new, b.priority)
        np.testing.assert_array_equal(a.prompt, b.prompt)
    path = tmp_path / "swing.json"
    s1.to_json(str(path))
    s2 = Scenario.from_json(str(path))
    assert s2.to_dict() == s1.to_dict()
    for a, b in zip(t1, s2.trace()):
        assert a.t == b.t
        np.testing.assert_array_equal(a.prompt, b.prompt)
    # a different seed must actually change the draw
    t3 = Scenario.swing(low_rps=4, high_rps=40, low_s=0.5, high_s=0.5,
                        seed=4, vocab=31).trace()
    assert [a.t for a in t3] != [a.t for a in t1]


@pytest.mark.parametrize("arrival", ["poisson", "heavy_tail", "burst"])
def test_arrival_processes_hold_offered_load(arrival):
    """Every interarrival process targets the same mean rate — they
    differ in variance, not offered load (open-loop invariant)."""
    s = Scenario(seed=7, vocab=31,
                 phases=[{"duration_s": 20.0, "rate_rps": 20.0,
                          "arrival": arrival}])
    tr = s.trace()
    assert 0.5 * 400 < len(tr) < 2.0 * 400
    assert all(0 <= a.t < 20.0 for a in tr)
    assert all(tr[i].t <= tr[i + 1].t for i in range(len(tr) - 1))
    gaps = np.diff([a.t for a in tr])
    if arrival == "burst":
        # clustered: most gaps are the tiny intra-burst spacing
        assert np.mean(gaps < 0.5 / 20.0) > 0.5
    if arrival == "heavy_tail":
        # Pareto(1.8): a few gaps far beyond the exponential scale
        assert gaps.max() > 5.0 / 20.0


def test_zipf_users_share_persistent_prefixes():
    """Hot users dominate and every request of a user starts with the
    same persistent prefix — the traffic shape the PrefixCache needs."""
    s = Scenario(seed=5, vocab=31, n_users=32, user_prefix_len=6,
                 phases=[{"duration_s": 30.0, "rate_rps": 10.0}])
    tr = s.trace()
    counts: dict = {}
    for a in tr:
        counts[a.user] = counts.get(a.user, 0) + 1
    top = max(counts.values())
    assert top > 3 * (len(tr) / len(counts))     # zipf skew, not uniform
    by_user: dict = {}
    for a in tr:
        head = tuple(int(x) for x in a.prompt[:6])
        by_user.setdefault(a.user, set()).add(head)
    assert all(len(heads) == 1 for heads in by_user.values())
    for u in by_user:
        np.testing.assert_array_equal(
            s.user_prefix(u),
            np.asarray(sorted(by_user[u])[0], np.int32))
    # priorities come from the declared classes
    assert {a.priority for a in tr} <= {p for p, _ in s.priorities}


def test_replay_is_open_loop_and_records_submit_errors():
    """replay() paces by the trace clock (never by completions) and a
    synchronous submit raise is an outcome, not a crash."""
    s = Scenario(seed=1, vocab=31,
                 phases=[{"duration_s": 0.4, "rate_rps": 50.0}])
    tr = s.trace()
    calls = []

    def submit(arrival):
        calls.append(arrival)
        if len(calls) == 3:
            raise RuntimeError("shed")
        return ("future", len(calls))

    recs = workload.replay(submit, tr, time_scale=0.5)
    assert len(recs) == len(tr) == len(calls)
    assert isinstance(recs[2]["error"], RuntimeError)
    assert recs[2]["future"] is None
    ok = [r for r in recs if r["error"] is None]
    assert all(r["future"] is not None for r in ok)
    for r in recs:    # open loop: never submitted before its due time
        assert r["t_submit"] >= r["arrival"].t * 0.5 - 1e-3
    stopped = workload.replay(submit, tr,
                              time_scale=0.0, stop=lambda: True)
    assert stopped == []


# ---------------------------------------------------------------------------
# elastic ReplicaSet membership (one shared fleet, lifecycle order)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def efleet(gpt):
    router = Router(gpt, replicas=2,
                    engine_kw=dict(max_slots=2, block_size=8),
                    hedge=False, retry_budget=3, liveness_timeout_s=30.0,
                    backoff_base_s=0.02, name="ef").start()
    yield router
    router.shutdown(drain=True)


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(np.int32)


def test_router_never_routes_to_starting_or_draining(efleet):
    """_pick sees only state=="healthy" — starting newcomers and
    draining victims are invisible to routing, hedging, and replay."""
    rs = efleet.replica_set
    assert "draining" in REPLICA_STATE_CODES
    r0, r1 = rs.replicas[0], rs.replicas[1]
    for state in ("starting", "draining"):
        r1.state = state
        try:
            assert [r.name for r in rs.healthy()] == [r0.name]
            for _ in range(8):
                assert efleet._pick(frozenset()).name == r0.name
            assert efleet._pick(frozenset({r0})) is None
        finally:
            r1.state = "healthy"


def test_scale_up_fault_rolls_back_membership(efleet):
    """A raise at serving.scale_up aborts the grow atomically: the
    half-added replica never becomes a member."""
    rs = efleet.replica_set
    before = [r.name for r in rs.replicas]
    with faults.ChaosSchedule("serving.scale_up@1:raise") as ch:
        with pytest.raises(faults.FaultError):
            rs.add_replica()
        ch.verify()
    assert [r.name for r in rs.replicas] == before
    assert rs.member_replicas() == len(before)
    p = _prompt(21, 6)
    ref = efleet.submit(p, max_new_tokens=4).result(120)
    np.testing.assert_array_equal(
        efleet.submit(p, max_new_tokens=4).result(120), ref)


def test_add_replica_under_load_compiles_once(efleet):
    """Growing the fleet mid-traffic: the newcomer warms up behind the
    single-trace restart path (one decode + one cow compile) and joins
    without disturbing in-flight requests."""
    rs = efleet.replica_set
    futs = [efleet.submit(_prompt(30 + i, 5 + i % 3), max_new_tokens=5)
            for i in range(8)]
    added = monitor.stat_get("fleet.scale_events_up")
    newcomer = rs.add_replica()          # blocking build under load
    assert monitor.stat_get("fleet.scale_events_up") == added + 1
    assert efleet.metrics.get("replicas_added") >= 1
    for f in futs:
        assert f.result(120) is not None
    assert _wait(lambda: newcomer.state == "healthy", 30)
    assert rs.compile_counts()[newcomer.name] == {"decode": 1, "cow": 1}
    assert rs.member_replicas() == 3
    # the newcomer actually serves, and bitwise like the veterans
    p = _prompt(40, 6)
    ref = efleet.submit(p, max_new_tokens=5).result(120)
    for _ in range(6):
        np.testing.assert_array_equal(
            efleet.submit(p, max_new_tokens=5).result(120), ref)
    assert rs.compile_counts()[newcomer.name] == {"decode": 1, "cow": 1}


def test_drain_then_evict_with_chaos_at_the_drain_sites(efleet):
    """Scale-down under chaos: a delay at serving.scale_down and a
    raise at the first serving.drain eviction attempt — the watchdog
    retries and the victim still leaves with zero lost requests."""
    rs = efleet.replica_set
    victim = rs.replicas[-1]             # the newcomer from the test above
    futs = [efleet.submit(_prompt(50 + i, 5), max_new_tokens=4)
            for i in range(6)]
    downs = monitor.stat_get("fleet.scale_events_down")
    with faults.ChaosSchedule("serving.scale_down@1:delay:0.005",
                              "serving.drain@1:raise") as ch:
        got = efleet.remove_replica(victim.name, drain=True)
        assert got is victim and victim.state == "draining"
        for f in futs:                   # nothing in flight is lost
            assert f.result(120) is not None
        assert _wait(lambda: victim.name not in
                     [r.name for r in rs.replicas], 30)
        ch.verify()
    assert monitor.stat_get("fleet.scale_events_down") == downs + 1
    assert efleet.metrics.get("drain_errors") >= 1   # the faulted attempt
    assert efleet.metrics.get("replicas_removed") >= 1
    assert victim.state == "stopped"
    assert rs.member_replicas() == 2
    assert rs.replica_seconds() > 0.0


def test_kill_during_drain_replays_bitwise(efleet):
    """The hard scale-down case: the draining victim dies with work
    still on it. First-wins futures + failover replay must deliver
    every request exactly once, bitwise equal to a clean run — and the
    dead victim must be dropped, not restarted."""
    rs = efleet.replica_set
    prompts = [(_prompt(60 + i, 5 + i % 3), 4 + i % 2) for i in range(6)]
    refs = [efleet.submit(p, max_new_tokens=m).result(120)
            for p, m in prompts]
    victim = rs.replicas[0]
    restarts = efleet.metrics.get("replica_restarts")
    with faults.inject(
            f"serving.replica_step[{victim.name}]@*:delay:0.02"):
        futs = [efleet.submit(p, max_new_tokens=m) for p, m in prompts]
        efleet.remove_replica(victim.name, drain=True)
        rs.kill(victim.name, "chaos: died mid-drain")
        for f, ref in zip(futs, refs):
            np.testing.assert_array_equal(f.result(120), ref)
    assert _wait(lambda: victim.name not in
                 [r.name for r in rs.replicas], 30)
    assert efleet.metrics.get("fleet_completed") >= 2 * len(prompts)
    assert efleet.metrics.get("replica_restarts") == restarts
    assert rs.member_replicas() == 1


def test_remove_last_healthy_replica_is_rejected(efleet):
    """Scale-down must never take the fleet dark."""
    rs = efleet.replica_set
    while len(rs.healthy()) > 1:        # independent of story state
        victim = rs.healthy()[-1]
        efleet.remove_replica(victim.name, drain=True)
        assert _wait(lambda: victim.name not in
                     [r.name for r in rs.replicas], 30)
    (last,) = rs.healthy()
    with pytest.raises(ValueError):
        efleet.remove_replica(last.name)
    with pytest.raises(KeyError):
        efleet.remove_replica("ef.nope")
    snap = efleet.snapshot()
    assert snap["live_replicas"] == 1
    assert snap["replica_seconds"] > 0.0
    for rep in snap["replicas"]:
        assert rep["uptime_s"] >= 0.0 and rep["beat_age_s"] >= 0.0
        assert rep["state"] in REPLICA_STATE_CODES


# ---------------------------------------------------------------------------
# Autoscaler control law (fake fleet, injected clock — no engines)
# ---------------------------------------------------------------------------


class _FakeReplica:
    def __init__(self, name, index):
        self.name, self.index, self.load = name, index, 0


class _FakeFleet:
    def __init__(self, n=1):
        self.replicas = [_FakeReplica(f"f.r{i}", i) for i in range(n)]
        self.adds = 0
        self.removed: list = []
        self.slots_per = 2

    def live_replicas(self):
        return len(self.replicas)

    def member_replicas(self):
        return len(self.replicas)

    def healthy(self):
        return list(self.replicas)

    def capacity(self):
        return len(self.replicas) * (self.slots_per + 64)

    def slot_capacity(self):
        return len(self.replicas) * self.slots_per

    def in_flight(self):
        return 0

    def add_replica(self):
        self.adds += 1
        r = _FakeReplica(f"f.r{len(self.replicas)}", len(self.replicas))
        self.replicas.append(r)
        return r

    def remove_replica(self, name, drain=True):
        self.removed.append((name, drain))
        self.replicas = [r for r in self.replicas if r.name != name]


class _FakeRouter:
    def __init__(self, n=1):
        self.replica_set = _FakeFleet(n)
        self.metrics = ServingMetrics()
        self.brownout_active = False
        self.in_flight = 0
        self.name = "f"
        self.autoscaler = None


def _burn(router, ms=400.0, n=8):
    """Fresh completions at `ms` e2e latency."""
    for _ in range(n):
        router.metrics.inc("fleet_completed")
        router.metrics.observe_latency("e2e", ms / 1e3)


def test_autoscaler_slo_burn_scales_up_with_cooldown():
    fr = _FakeRouter(1)
    asc = Autoscaler(fr, min_replicas=1, max_replicas=3, slo_p99_ms=100,
                     cooldown_s=1.0, clock=lambda: 0.0)
    assert fr.autoscaler is asc
    _burn(fr)
    sig = asc.tick(now=0.0)
    assert sig["over_slo"] and sig["overloaded"]
    asc._scale_thread.join(5)
    assert fr.replica_set.adds == 1 and asc.decisions["up"] == 1
    _burn(fr)
    asc.tick(now=0.5)                       # in cooldown: no second grow
    assert fr.replica_set.adds == 1
    _burn(fr)
    asc.tick(now=1.5)
    asc._scale_thread.join(5)
    assert fr.replica_set.adds == 2 and asc.target == 3
    _burn(fr)
    asc.tick(now=3.0)                       # at max: hold
    assert fr.replica_set.adds == 2
    assert asc.violation_s > 0.0
    assert monitor.stat_get("fleet.live_replicas") == 3
    assert monitor.stat_get("fleet.target_replicas") == 3
    assert monitor.stat_get("fleet.slo_violation_ms") == \
        int(asc.violation_s * 1e3)


def test_autoscaler_stale_window_reads_idle_and_shrinks():
    """Old congested samples must not pin the fleet at peak: with no
    fresh completions for a cooldown the p99 window is stale, the fleet
    reads idle, and shrinks back — but never below min_replicas."""
    fr = _FakeRouter(4)
    asc = Autoscaler(fr, min_replicas=2, max_replicas=4, slo_p99_ms=100,
                     cooldown_s=0.5, clock=lambda: 0.0)
    _burn(fr, ms=900.0)
    assert asc.tick(now=0.0)["over_slo"]    # fresh burn reads overloaded
    burn0 = asc.violation_s
    # traffic stops: same samples, no new completions
    sig = asc.tick(now=2.0)
    assert not sig["over_slo"] and sig["idle"]
    assert asc.violation_s == burn0         # stale window burns no budget
    asc.tick(now=3.0)                       # idle sustained -> shrink
    assert fr.replica_set.removed == [("f.r3", True)]   # newest-first
    asc.tick(now=9.0)
    assert fr.replica_set.removed == [("f.r3", True), ("f.r2", True)]
    asc.tick(now=15.0)                      # at min: hold
    assert len(fr.replica_set.removed) == 2
    assert fr.replica_set.live_replicas() == 2 == asc.min_replicas


def test_autoscaler_backlog_pressure_needs_no_latency_samples():
    """A stalled fleet (e.g. the only replica is rebuilding) emits no
    completions at all — backlog pressure still reads overloaded, and
    holds `idle` off while work is outstanding."""
    fr = _FakeRouter(1)
    asc = Autoscaler(fr, min_replicas=1, max_replicas=2, slo_p99_ms=100,
                     cooldown_s=0.5, clock=lambda: 0.0)
    fr.in_flight = 20                       # 10x the fleet's 2 slots
    sig = asc.tick(now=0.0)
    assert sig["pressure"] >= asc.backlog_factor
    assert sig["overloaded"] and not sig["idle"] and not sig["over_slo"]
    asc._scale_thread.join(5)
    assert fr.replica_set.adds == 1         # fleet now has 4 slots
    fr.in_flight = 9                        # above slots: not idle yet,
    sig = asc.tick(now=1.0)                 # but not backlogged either
    assert not sig["idle"] and not sig["overloaded"]
    fr.in_flight = 0
    assert asc.tick(now=2.0)["idle"]


def test_autoscaler_validates_bounds_and_reads_flags():
    fr = _FakeRouter(1)
    with pytest.raises(ValueError):
        Autoscaler(fr, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        Autoscaler(fr, low_water=0.9, high_water=0.5)
    saved = get_flags(["FLAGS_fleet_min_replicas",
                       "FLAGS_fleet_max_replicas",
                       "FLAGS_fleet_scale_cooldown_s",
                       "FLAGS_fleet_slo_p99_ms"])
    assert saved == {"FLAGS_fleet_min_replicas": 1,
                     "FLAGS_fleet_max_replicas": 8,
                     "FLAGS_fleet_scale_cooldown_s": 5.0,
                     "FLAGS_fleet_slo_p99_ms": 500.0}
    try:
        set_flags({"FLAGS_fleet_min_replicas": 2,
                   "FLAGS_fleet_max_replicas": 5,
                   "FLAGS_fleet_scale_cooldown_s": 1.5,
                   "FLAGS_fleet_slo_p99_ms": 80.0})
        asc = Autoscaler(_FakeRouter(2))    # defaults come from flags
        assert (asc.min_replicas, asc.max_replicas) == (2, 5)
        assert (asc.cooldown_s, asc.slo_p99_ms) == (1.5, 80.0)
    finally:
        set_flags(saved)
    assert flag("FLAGS_fleet_max_replicas") == 8


# ---------------------------------------------------------------------------
# autoscaler integration + observability
# ---------------------------------------------------------------------------


def test_autoscaler_grows_and_shrinks_a_real_fleet(gpt):
    """End to end: a Router started with `autoscale=` rides backlog
    pressure up to a second replica (compiled exactly once, under
    load), then drains back to the floor when traffic stops."""
    router = Router(gpt, replicas=1,
                    engine_kw=dict(max_slots=1, block_size=8),
                    hedge=False, liveness_timeout_s=30.0,
                    autoscale=dict(min_replicas=1, max_replicas=2,
                                   slo_p99_ms=50.0, cooldown_s=0.3,
                                   window=16),
                    name="af").start()
    try:
        asc = router.autoscaler
        assert asc is not None
        futs = [router.submit(_prompt(80 + i, 4 + i % 4),
                              max_new_tokens=4) for i in range(24)]
        # backlog pressure trips a grow; the build may outlive the
        # burst, so wait on the decision + landed build, not on
        # catching the transient two-replica window
        assert _wait(lambda: asc.decisions["up"] >= 1, 60)
        assert _wait(lambda: asc._scale_thread is not None, 10)
        asc._scale_thread.join(60)
        assert router.metrics.get("replicas_added") >= 1
        assert router.metrics.get("scale_failures") == 0
        for f in futs:
            assert f.result(120) is not None
        for name, counts in router.compile_counts().items():
            assert counts == {"decode": 1, "cow": 1}, (name, counts)
        snap = router.snapshot()["autoscaler"]
        assert snap["decisions"]["up"] >= 1
        assert snap["target"] in (1, 2)     # 1 if the shrink already hit
        # traffic is gone: drain back to the one-replica floor
        assert _wait(lambda: router.replica_set.member_replicas() == 1
                     and router.replica_set.live_replicas() == 1, 60)
        assert router.snapshot()["autoscaler"]["decisions"]["down"] >= 1
        p = _prompt(99, 5)
        ref = router.submit(p, max_new_tokens=4).result(120)
        np.testing.assert_array_equal(
            router.submit(p, max_new_tokens=4).result(120), ref)
    finally:
        router.shutdown(drain=True)


def test_fleet_prometheus_family_and_snapshot_mirror(efleet):
    """The paddle_fleet_* family renders with correct types and the
    observe.snapshot()["fleet"] mirror agrees with the registry."""
    fr = _FakeRouter(2)
    asc = Autoscaler(fr, min_replicas=1, max_replicas=4, slo_p99_ms=100,
                     cooldown_s=5.0, clock=lambda: 0.0)
    asc.tick(now=0.0)
    text = observe.prometheus_text(fleet=efleet.snapshot())
    assert "# TYPE paddle_fleet_target_replicas gauge" in text
    assert "# TYPE paddle_fleet_live_replicas gauge" in text
    assert "# TYPE paddle_fleet_scale_events_total counter" in text
    assert 'paddle_fleet_scale_events_total{direction="up"}' in text
    assert 'paddle_fleet_scale_events_total{direction="down"}' in text
    assert "paddle_fleet_slo_violation_seconds_total" in text
    assert "paddle_serving_replica_uptime_seconds" in text
    assert "paddle_serving_replica_beat_age_seconds" in text
    mirror = observe.snapshot()["fleet"]
    assert mirror["target_replicas"] == \
        monitor.stat_get("fleet.target_replicas")
    assert mirror["live_replicas"] == \
        monitor.stat_get("fleet.live_replicas")
    assert mirror["scale_events_up"] == \
        monitor.stat_get("fleet.scale_events_up")
    assert mirror["scale_events_down"] == \
        monitor.stat_get("fleet.scale_events_down")
    assert mirror["slo_violation_seconds"] == pytest.approx(
        monitor.stat_get("fleet.slo_violation_ms") / 1e3)


# ---------------------------------------------------------------------------
# bench front doors
# ---------------------------------------------------------------------------


def test_bench_serving_replays_a_trace_file(tmp_path):
    """bench_serving.py --trace <scenario.json> replays the spec
    open-loop and emits the BENCH_SERVING_TRACE record."""
    spec = Scenario.swing(low_rps=3, high_rps=12, low_s=0.5, high_s=0.5,
                          seed=2, vocab=31, prompt_len=(3, 5),
                          max_new=(2, 3))
    path = tmp_path / "swing.json"
    spec.to_json(str(path))
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench_serving.py"),
         "--trace", str(path), "--hidden", "16", "--layers", "1",
         "--heads", "2", "--vocab", "31", "--max-seq-len", "32",
         "--max-slots", "4"],
        capture_output=True, text=True, timeout=420,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    assert final["bench"] == "BENCH_SERVING_TRACE"
    assert final["scenario"]["name"] == spec.name
    assert final["arrivals"] == len(spec.trace())
    assert final["goodput"] == 1.0


@pytest.mark.slow
def test_bench_fleet_smoke():
    """The full elastic-fleet certification: static-peak vs autoscaled
    vs chaos legs of the 24x swing; asserts zero lost/duplicated, the
    compile-once invariant, chip-hour savings, and fired==planned for
    every scale-event chaos site."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench_fleet.py"), "--smoke"],
        capture_output=True, text=True, timeout=540,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-3000:]
    final = json.loads(proc.stdout.strip().splitlines()[-2])
    assert final["bench"] == "BENCH_FLEET"
    assert final["chaos_goodput"] == 1.0
    assert final["chip_fraction_vs_static"] < 1.0
    for leg in ("static", "autoscaled", "chaos"):
        assert final[leg]["lost"] == 0 and final[leg]["duplicated"] == 0
