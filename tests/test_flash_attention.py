"""flash_attention op: fwd + grad vs the jnp SDPA reference, fp32/bf16,
causal and full; the Pallas kernels are exercised in interpreter mode.

Ref parity intent: paddle/fluid/operators/fused/multihead_matmul_op.cu
tested via unittests comparing against the unfused composition.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.op_registry import has_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import fused_ops


def _sdpa_ref(q, k, v, causal, scale=None):
    import math
    d = q.shape[-1]
    s = scale or 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * s
    if causal:
        # bottom-right alignment, same as ops/nn_ops.py sdpa
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _rand_qkv(rng, b, h, s, d, dtype):
    shape = (b, h, s, d)
    return tuple(jnp.asarray(rng.standard_normal(shape), dtype)
                 for _ in range(3))


def test_registered():
    assert has_op("flash_attention")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_matches_sdpa(causal, dtype):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, 2, 3, 37, 16, dtype)
    got = fused_ops.flash_attention(q, k, v, is_causal=causal)
    want = _sdpa_ref(q, k, v, causal)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_sdpa(causal):
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, 1, 2, 29, 8, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(fused_ops.flash_attention(q, k, v, is_causal=causal)
                       ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_tape_autograd_through_dispatch():
    rng = np.random.default_rng(2)
    qn = rng.standard_normal((1, 2, 12, 8)).astype(np.float32)
    kn = rng.standard_normal((1, 2, 12, 8)).astype(np.float32)
    vn = rng.standard_normal((1, 2, 12, 8)).astype(np.float32)
    q, k, v = Tensor(qn, stop_gradient=False), Tensor(kn, stop_gradient=False), \
        Tensor(vn, stop_gradient=False)
    out = apply("flash_attention", q, k, v, is_causal=True)
    out.backward(Tensor(np.ones(out.shape, np.float32)))
    want = jax.grad(
        lambda a: jnp.sum(_sdpa_ref(a, jnp.asarray(kn), jnp.asarray(vn),
                                    True)))(jnp.asarray(qn))
    np.testing.assert_allclose(q.grad.numpy(), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernels_interpret_mode(causal):
    """Run the actual Pallas kernels (interpreter) vs the jnp path."""
    rng = np.random.default_rng(3)
    # deliberately unaligned seq to exercise padding/masking
    q, k, v = _rand_qkv(rng, 1, 1, 70, 8, jnp.float32)
    os.environ["PADDLE_TPU_FLASH_FORCE"] = "pallas"
    try:
        o_pl = fused_ops.flash_attention(q, k, v, is_causal=causal)
        gq_pl, gk_pl, gv_pl = jax.grad(
            lambda a, b, c: jnp.sum(
                fused_ops.flash_attention(a, b, c, is_causal=causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
    finally:
        os.environ.pop("PADDLE_TPU_FLASH_FORCE", None)
    o_ref = _sdpa_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(o_pl), np.asarray(o_ref),
                               rtol=1e-5, atol=1e-5)
    gq, gk, gv = jax.grad(
        lambda a, b, c: jnp.sum(_sdpa_ref(a, b, c, causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq_pl), np.asarray(gq),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gk_pl), np.asarray(gk),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gv_pl), np.asarray(gv),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_cross_attention_different_kv_len(causal):
    """KV-cache decode shape: q shorter than kv; causal must be
    bottom-right aligned, matching the sdpa fallback."""
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((1, 2, 9, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 21, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 21, 8)), jnp.float32)
    got = fused_ops.flash_attention(q, k, v, is_causal=causal)
    want = _sdpa_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    os.environ["PADDLE_TPU_FLASH_FORCE"] = "pallas"
    try:
        got_pl = fused_ops.flash_attention(q, k, v, is_causal=causal)
    finally:
        os.environ.pop("PADDLE_TPU_FLASH_FORCE", None)
    np.testing.assert_allclose(np.asarray(got_pl), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# -- in-kernel dropout (jnp fallback on CPU; same code shape as pallas) -----


def test_flash_dropout_deterministic_and_seed_sensitive():
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_ops import _flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 2, 64, 32).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, 64, 32).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, 64, 32).astype(np.float32))
    s1 = jnp.asarray(11, jnp.int32)
    s2 = jnp.asarray(12, jnp.int32)
    a = np.asarray(_flash_attention(q, k, v, s1, False, 0.2, 0.3))
    b = np.asarray(_flash_attention(q, k, v, s1, False, 0.2, 0.3))
    c = np.asarray(_flash_attention(q, k, v, s2, False, 0.2, 0.3))
    np.testing.assert_allclose(a, b)  # same seed -> same mask
    assert np.abs(a - c).max() > 1e-4  # different seed -> different mask
    # dropped output is an unbiased-ish estimate of the dense one
    dense = np.asarray(_flash_attention(q, k, v, s1, False, 0.2, 0.0))
    assert 0.0 < np.abs(a - dense).mean() < 1.0


def test_flash_dropout_backward_mask_matches_forward():
    """The backward must regenerate the forward's mask: for a linear loss
    sum(o * w), dv must equal (dropped p)^T w — recover the mask from dv
    and check the forward output reproduces exactly."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_ops import _flash_attention

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 1, 32, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 1, 32, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 1, 32, 16).astype(np.float32))
    seed = jnp.asarray(5, jnp.int32)
    p_drop = 0.4

    def out_sum(v):
        return jnp.sum(
            _flash_attention(q, k, v, seed, False, 0.25, p_drop))

    o = _flash_attention(q, k, v, seed, False, 0.25, p_drop)
    dv = jax.grad(out_sum)(v)
    # dv[j] = sum_i pd_ij (cotangent all-ones); rebuild o from pd via dv:
    # o_i = sum_j pd_ij v_j. Check global consistency: sum(o) == sum(dv*v)
    np.testing.assert_allclose(float(jnp.sum(o)),
                               float(jnp.sum(dv * v)), rtol=1e-4)


def test_flash_dropout_grad_matches_jax_ad_of_forward():
    """jnp fallback: custom bwd vs jax AD of the (pure) fwd formula must
    agree — certifies the hand-derived dropout backward."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_ops import _flash_fwd_jnp

    rng = np.random.RandomState(4)
    q3 = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
    k3 = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
    v3 = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
    seed = jnp.asarray(9, jnp.int32)

    from paddle_tpu.ops.fused_ops import _flash_bwd_jnp

    o, lse = _flash_fwd_jnp(q3, k3, v3, seed, 0.25, False, 0.3)
    g = jnp.ones_like(o)
    dq, dk, dv = _flash_bwd_jnp(q3, k3, v3, o, lse, g, seed, 0.25, False,
                                0.3)

    def f(q3, k3, v3):
        return jnp.sum(_flash_fwd_jnp(q3, k3, v3, seed, 0.25, False,
                                      0.3)[0])

    rq, rk, rv = jax.grad(f, argnums=(0, 1, 2))(q3, k3, v3)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rk),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rv),
                               rtol=1e-4, atol=1e-5)


def test_sdpa_dropout_routes_to_flash_and_trains():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.tensor import Tensor

    paddle.seed(1)
    rng = np.random.RandomState(0)
    q = Tensor(rng.randn(2, 16, 2, 8).astype(np.float32),
               stop_gradient=False)
    out = F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                         training=True)
    assert out.shape == [2, 16, 2, 8]
    out.backward(Tensor(np.ones((2, 16, 2, 8), np.float32)))
    assert q.grad is not None
    assert np.isfinite(q.grad.numpy()).all()


@pytest.mark.tpu
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="pallas dropout PRNG requires a real TPU "
                           "(interpret mode cannot execute the "
                           "prng-in-loop jaxpr)")
def test_pallas_dropout_masks_consistent_on_tpu():
    """Run the ACTUAL pallas kernels with dropout: the forward and both
    backward kernels must regenerate the same tile-seeded mask — recover
    the dropped-prob matrix from the forward (identity-v probe) and
    compare against the masks the backward kernels apply."""
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_ops import (
        _flash_bwd_pallas, _flash_fwd_pallas,
    )

    rng = np.random.RandomState(0)
    # d == s so the identity matrix can serve as v (probing pd)
    s = 128
    q3 = jnp.asarray(rng.randn(1, s, s).astype(np.float32) * 0.1)
    k3 = jnp.asarray(rng.randn(1, s, s).astype(np.float32) * 0.1)
    eye = jnp.eye(s, dtype=jnp.float32)[None]
    seed = jnp.asarray(21, jnp.int32)
    p_drop = 0.4

    o, lse = _flash_fwd_pallas(q3, k3, eye, seed, 0.2, False, p_drop)
    pd_fwd = np.asarray(o[0])  # dropped, rescaled, normalised probs
    # determinism across calls
    o2, _ = _flash_fwd_pallas(q3, k3, eye, seed, 0.2, False, p_drop)
    np.testing.assert_allclose(pd_fwd, np.asarray(o2[0]))
    frac = (pd_fwd == 0).mean()
    assert 0.25 < frac < 0.55, frac

    # undropped normalised probs (reference softmax)
    sfull = np.asarray(q3[0]) @ np.asarray(k3[0]).T * 0.2
    p_ref = np.exp(sfull - sfull.max(-1, keepdims=True))
    p_ref /= p_ref.sum(-1, keepdims=True)
    mask = np.where(pd_fwd > 0, 1.0 / (1.0 - p_drop), 0.0)
    # dropped entries are EXACT zeros; kept entries match within TPU
    # default f32-matmul precision (~3e-3 relative)
    assert (pd_fwd[mask == 0] == 0).all()
    np.testing.assert_allclose(pd_fwd, p_ref * mask, rtol=1e-2,
                               atol=1e-4)

    # dkv kernel regenerates the same mask: dv = pd^T @ do
    do = jnp.ones_like(o)
    dq, dk, dv = _flash_bwd_pallas(q3, k3, eye, o, lse, do, seed,
                                   0.2, False, p_drop)
    np.testing.assert_allclose(np.asarray(dv[0])[:, 0],
                               pd_fwd.sum(axis=0), rtol=1e-2,
                               atol=1e-3)

    # dq kernel: reference dq from the recovered mask must match
    delta = (np.asarray(do[0]) * np.asarray(o[0])).sum(-1)
    dp = np.asarray(do[0]) @ np.asarray(eye[0]).T
    ds = p_ref * (dp * mask - delta[:, None])
    dq_ref = ds @ np.asarray(k3[0]) * 0.2
    np.testing.assert_allclose(np.asarray(dq[0]), dq_ref, rtol=1e-2,
                               atol=1e-3)
