"""fleet.utils.fs, fleet.metrics, and the op-version registry.

Ref intent: unittests/test_fs.py, test_fleet_metric.py,
test_op_version.py — filesystem abstraction round trips, global metric
reduction (single-process == local; PS mode merges through tables),
and version-map embedding/checking on saved inference artifacts.
"""

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.fleet import metrics
from paddle_tpu.distributed.fleet.utils import LocalFS
from paddle_tpu.framework import op_version


def test_local_fs_roundtrip(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "a" / "b")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = d + "/x.txt"
    fs.touch(f)
    assert fs.is_file(f)
    with pytest.raises(Exception):
        fs.touch(f, exist_ok=False)
    fs.mv(f, d + "/y.txt")
    assert fs.is_file(d + "/y.txt") and not fs.is_exist(f)
    dirs, files = fs.ls_dir(d)
    assert files == ["y.txt"] and dirs == []
    fs.delete(d)
    assert not fs.is_exist(d)
    assert not fs.need_upload_download()


def test_metrics_local_fallback():
    # single process: reductions are identity
    assert float(metrics.sum(3.0)) == 3.0
    assert float(metrics.acc(8.0, 10.0)) == pytest.approx(0.8)
    np.testing.assert_allclose(metrics.sum(np.array([1.0, 2.0])),
                               [1.0, 2.0])


def test_metrics_auc_matches_streaming_metric():
    # merge two trainers' Auc buckets -> same value as one combined Auc
    from paddle_tpu.metric import Auc

    rng = np.random.RandomState(0)
    preds = rng.rand(200, 2).astype(np.float64)
    preds[:, 0] = 1.0 - preds[:, 1]
    labels = (rng.rand(200) > 0.5).astype(np.int64)[:, None]

    combined = Auc()
    combined.update(preds, labels)

    a, b = Auc(), Auc()
    a.update(preds[:100], labels[:100])
    b.update(preds[100:], labels[100:])
    # local-mode _reduce is identity, so pass pre-summed buckets
    got = metrics.auc(
        np.asarray(a._stat_pos) + np.asarray(b._stat_pos),
        np.asarray(a._stat_neg) + np.asarray(b._stat_neg))
    assert got == pytest.approx(combined.accumulate(), abs=1e-9)


def test_metrics_ps_mode_sum(tmp_path):
    server = ps.PSServer("127.0.0.1:0").start()
    rm = ps.PSRoleMaker(server_endpoints=[f"127.0.0.1:{server.port}"],
                        role="TRAINER", n_trainers=1)
    rt = ps.init_runtime(rm, mode="sync")
    rt.init_worker()
    try:
        got = metrics.sum(np.array([2.0, 3.0]))
        np.testing.assert_allclose(got, [2.0, 3.0])
    finally:
        import paddle_tpu.distributed.ps.runtime as rtmod

        rt.stop_worker()
        server.stop()
        rtmod._runtime = None


def test_op_version_registry():
    v0 = op_version.get_op_version("matmul_v2")
    op_version.register_op_version("matmul_v2").new_attr(
        "test_attr", "testing only")
    try:
        assert op_version.get_op_version("matmul_v2") == v0 + 1
        vm = op_version.version_map()
        assert vm["matmul_v2"] == v0 + 1
        assert vm.get("relu", 0) >= 0
        mism = op_version.check_compatibility({"matmul_v2": v0 + 1})
        assert mism == []
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            mism = op_version.check_compatibility({"matmul_v2": 99})
        assert mism and "matmul_v2" in str(w[0].message)
        with pytest.raises(RuntimeError):
            op_version.check_compatibility({"matmul_v2": 99}, strict=True)
    finally:
        op_version._VERSIONS["matmul_v2"].pop()


def test_saved_model_embeds_versions(tmp_path):
    paddle.enable_static()
    main, startup = static.Program(), static.Program()
    try:
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3], "float32")
            out = paddle.tanh(x)
            exe = static.Executor()
            path = str(tmp_path / "m")
            static.save_inference_model(path, [x], [out], exe)
            import pickle

            meta = pickle.load(open(path + ".pdmodel", "rb"))
            assert "tanh" in meta["op_versions"]
            # load re-checks compatibility silently when maps agree
            prog, feeds, fetches = static.load_inference_model(path, exe)
            (got,) = exe.run(prog, feed={"x": np.ones((2, 3), np.float32)},
                             fetch_list=fetches)
            np.testing.assert_allclose(got, np.tanh(np.ones((2, 3))),
                                       rtol=1e-6)
    finally:
        paddle.disable_static()


def test_metrics_ps_mode_max_min_and_cleanup():
    """max/min must merge through identity-initialised scratch tables
    (zeros would poison them), and scratch tables must not leak."""
    import paddle_tpu.distributed.ps.runtime as rtmod

    server = ps.PSServer("127.0.0.1:0").start()
    rm = ps.PSRoleMaker(server_endpoints=[f"127.0.0.1:{server.port}"],
                        role="TRAINER", trainer_id=0, n_trainers=1)
    rt = ps.init_runtime(rm, mode="sync")
    rt.init_worker()
    try:
        assert float(metrics.max(-5.0)) == -5.0
        assert float(metrics.min(2.0)) == 2.0
        n = len(server._tables)
        metrics.sum(1.0)
        assert len(server._tables) == n  # per-call table deleted
    finally:
        rt.stop_worker()
        server.stop()
        rtmod._runtime = None
