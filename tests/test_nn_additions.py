"""3-D/adaptive pooling, transpose convs, small activations/losses, and
nn.utils norm hooks.

Ref parity: python/paddle/nn/layer/{pooling,conv,activation,common,
loss}.py + nn/utils/{weight_norm_hook,spectral_norm_hook}.py +
operators/{maxout_op,thresholded_relu_op,hierarchical_sigmoid_op}.cc.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor

pytestmark = pytest.mark.smoke


def _t(a):
    return Tensor(np.asarray(a, np.float32))


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# -- 3-D / adaptive pooling --------------------------------------------------

def test_max_avg_pool3d_shapes_and_values():
    x = _rand(2, 3, 4, 4, 4)
    out = F.max_pool3d(_t(x), 2, 2)
    assert list(out.shape) == [2, 3, 2, 2, 2]
    want = x.reshape(2, 3, 2, 2, 2, 2, 2, 2).transpose(
        0, 1, 2, 4, 6, 3, 5, 7).reshape(2, 3, 2, 2, 2, -1).max(-1)
    np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-6)
    avg = F.avg_pool3d(_t(x), 2, 2)
    wanta = x.reshape(2, 3, 2, 2, 2, 2, 2, 2).transpose(
        0, 1, 2, 4, 6, 3, 5, 7).reshape(2, 3, 2, 2, 2, -1).mean(-1)
    np.testing.assert_allclose(np.asarray(avg.numpy()), wanta, rtol=1e-6)


def test_adaptive_pool3d_uneven_bins():
    x = _rand(1, 2, 5, 7, 6)
    out = nn.AdaptiveAvgPool3D((2, 3, 4))(_t(x))
    assert list(out.shape) == [1, 2, 2, 3, 4]
    # paddle bin bounds: start floor(i*L/out), end ceil((i+1)*L/out)
    s0, e0 = 0, -(-5 // 2)  # first D bin: [0, 3)
    np.testing.assert_allclose(
        np.asarray(out.numpy())[0, 0, 0, 0, 0],
        x[0, 0, s0:e0, 0:3, 0:2].mean(), rtol=1e-6)
    mx = nn.AdaptiveMaxPool3D(2)(_t(x))
    assert list(mx.shape) == [1, 2, 2, 2, 2]


def test_adaptive_pool1d():
    x = _rand(2, 3, 12)
    out = nn.AdaptiveAvgPool1D(4)(_t(x))
    np.testing.assert_allclose(
        np.asarray(out.numpy()), x.reshape(2, 3, 4, 3).mean(-1),
        rtol=1e-6)
    mx = nn.AdaptiveMaxPool1D(3)(_t(x))
    np.testing.assert_allclose(
        np.asarray(mx.numpy()), x.reshape(2, 3, 3, 4).max(-1), rtol=1e-6)


# -- transpose convolutions --------------------------------------------------

def test_conv1d_transpose_matches_conv2d_transpose():
    paddle.seed(0)
    layer = nn.Conv1DTranspose(3, 5, 4, stride=2, padding=1)
    x = _rand(2, 3, 10, seed=1)
    out = layer(_t(x))
    assert list(out.shape) == [2, 5, 20]
    # torch-checked formula: L_out = (L-1)*s - 2p + k
    assert out.shape[2] == (10 - 1) * 2 - 2 * 1 + 4


def test_conv3d_transpose_shape_and_grad():
    paddle.seed(0)
    layer = nn.Conv3DTranspose(2, 4, 3, stride=2)
    x = _t(_rand(1, 2, 3, 4, 5, seed=2))
    out = layer(x)
    assert list(out.shape) == [1, 4, 7, 9, 11]
    out.sum().backward()
    assert layer.weight.grad is not None


# -- activations / distances -------------------------------------------------

def test_maxout():
    x = _rand(2, 6, 3, 3)
    out = nn.Maxout(3)(_t(x))
    want = x.reshape(2, 2, 3, 3, 3).max(2)
    np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-6)


def test_thresholded_relu():
    x = np.array([[-1.0, 0.5, 1.0, 2.5]], np.float32)
    out = nn.ThresholdedReLU(1.0)(_t(x))
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[0.0, 0.0, 0.0, 2.5]])


def test_pairwise_distance():
    x, y = _rand(3, 5, seed=3), _rand(3, 5, seed=4)
    out = nn.PairwiseDistance(p=2.0)(_t(x), _t(y))
    # eps is added to the SIGNED difference (reference semantics)
    want = np.linalg.norm(x - y + 1e-6, axis=-1)
    np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-5)


def test_alpha_dropout_moments_and_eval():
    layer = nn.AlphaDropout(p=0.3)
    layer.eval()
    x = _t(_rand(4, 8))
    np.testing.assert_array_equal(np.asarray(layer(x).numpy()),
                                  np.asarray(x.numpy()))
    layer.train()
    paddle.seed(7)
    big = _t(_rand(512, 512, seed=5))
    out = np.asarray(layer(big).numpy())
    # SELU-preserving: mean~0, var~1 for standard-normal input
    assert abs(out.mean()) < 0.05
    assert abs(out.std() - 1.0) < 0.1


def test_dropout3d_drops_whole_channels():
    layer = nn.Dropout3D(p=0.5)
    layer.train()
    paddle.seed(11)
    x = _t(np.ones((2, 8, 3, 3, 3), np.float32))
    out = np.asarray(layer(x).numpy())
    per_channel = out.reshape(2, 8, -1)
    for b in range(2):
        for c in range(8):
            vals = np.unique(per_channel[b, c])
            assert len(vals) == 1  # whole channel kept or dropped


# -- losses ------------------------------------------------------------------

def test_ctc_loss_layer():
    logits = _t(_rand(6, 2, 5, seed=6))
    labels = Tensor(np.array([[1, 2], [2, 3]], np.int32))
    loss = nn.CTCLoss()(logits, labels,
                        Tensor(np.array([6, 6], np.int32)),
                        Tensor(np.array([2, 2], np.int32)))
    assert np.asarray(loss.numpy()).shape == ()
    assert float(loss.numpy()) > 0


def test_hsigmoid_loss_default_tree():
    paddle.seed(0)
    hs = nn.HSigmoidLoss(8, 6)
    x = _t(_rand(4, 8, seed=7))
    label = Tensor(np.array([[0], [1], [4], [5]], np.int32))
    loss = hs(x, label)
    # reference semantics: per-sample [N, 1] losses, unreduced
    assert list(loss.shape) == [4, 1]
    assert (np.asarray(loss.numpy()) > 0).all()
    loss.mean().backward()
    assert hs.weight.grad is not None
    # a confident model drives the loss down: fit one batch
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=hs.parameters())
    first = None
    for _ in range(30):
        out = hs(x, label).mean()
        out.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(out.numpy())
    assert float(out.numpy()) < first * 0.2


def test_hsigmoid_loss_custom_path():
    hs = nn.HSigmoidLoss(8, 5, is_custom=True)
    pt = Tensor(np.array([[0, 1, -1], [0, 2, 3]], np.int32))
    pc = Tensor(np.array([[1, 0, 0], [0, 1, 1]], np.float32))
    x = _t(_rand(2, 8, seed=8))
    loss = hs(x, Tensor(np.array([[1], [2]], np.int32)), pt, pc)
    assert list(loss.shape) == [2, 1]
    assert (np.asarray(loss.numpy()) > 0).all()
    with pytest.raises(ValueError):
        hs(x, Tensor(np.array([[1], [2]], np.int32)))


# -- nn.utils hooks ----------------------------------------------------------

def test_weight_norm_roundtrip_and_grads():
    paddle.seed(0)
    lin = nn.Linear(6, 4)
    x = _t(_rand(2, 6, seed=9))
    ref = np.asarray(lin(x).numpy())
    nn.utils.weight_norm(lin, dim=0)
    names = dict(lin.named_parameters())
    assert any(k.endswith("weight_g") for k in names)
    np.testing.assert_allclose(np.asarray(lin(x).numpy()), ref,
                               rtol=1e-5)
    lin(x).sum().backward()
    assert lin.weight_g.grad is not None
    assert lin.weight_v.grad is not None
    nn.utils.remove_weight_norm(lin)
    np.testing.assert_allclose(np.asarray(lin(x).numpy()), ref,
                               rtol=1e-5)
    assert not any(k.endswith("weight_g")
                   for k in dict(lin.named_parameters()))


def test_remove_weight_norm_after_optimizer_step():
    """Folding must use the CURRENT g/v, not the last-materialized
    weight from the previous forward."""
    paddle.seed(1)
    lin = nn.Linear(4, 3)
    x = _t(_rand(2, 4, seed=11))
    nn.utils.weight_norm(lin)
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=lin.parameters())
    lin(x).sum().backward()
    opt.step()          # g/v updated; no forward ran since
    opt.clear_grad()
    want = np.asarray(lin(x).numpy())   # effective post-step output
    nn.utils.remove_weight_norm(lin)
    np.testing.assert_allclose(np.asarray(lin(x).numpy()), want,
                               rtol=1e-6)


def test_conv1d_transpose_asymmetric_padding():
    w = _t(_rand(3, 5, 4, seed=14))
    x = _t(_rand(2, 3, 8, seed=15))
    out = F.conv1d_transpose(x, w, stride=2, padding=[1, 2])
    # L_out = (L-1)*s + k - pad_lo - pad_hi = 7*2 + 4 - 3 = 15
    assert out.shape[2] == 15
    sym = F.conv1d_transpose(x, w, stride=2, padding=1)
    assert sym.shape[2] == 16


def test_weight_norm_g_is_1d():
    conv = nn.Conv2D(3, 8, 3)
    nn.utils.weight_norm(conv, dim=0)
    # reference norm_except_dim shape: 1-D [k], not keepdims
    assert list(np.asarray(conv.weight_g.numpy()).shape) == [8]
    x = _t(_rand(1, 3, 8, 8, seed=16))
    assert conv(x).shape[1] == 8


def test_spectral_norm_default_dim_linear_vs_conv():
    # Linear/Conv*DTranspose default to dim=1 (reference), others dim=0
    lin = nn.Linear(6, 4)
    nn.utils.spectral_norm(lin)
    assert lin.weight_u.shape[0] == 4      # out axis of [in, out]
    conv = nn.Conv2D(3, 8, 3)
    nn.utils.spectral_norm(conv)
    assert conv.weight_u.shape[0] == 8     # out axis of [out, in, kh, kw]


def test_conv1d_transpose_nlc_layout():
    paddle.seed(0)
    w = _t(_rand(3, 5, 4, seed=12))
    x = _rand(2, 3, 10, seed=13)
    ncl = F.conv1d_transpose(_t(x), w, stride=2)
    nlc = F.conv1d_transpose(_t(x.transpose(0, 2, 1)), w, stride=2,
                             data_format="NLC")
    np.testing.assert_allclose(
        np.asarray(nlc.numpy()).transpose(0, 2, 1),
        np.asarray(ncl.numpy()), rtol=1e-5)


def test_weight_norm_dim_none_scalar_g():
    lin = nn.Linear(5, 3)
    nn.utils.weight_norm(lin, dim=None)
    assert np.asarray(lin.weight_g.numpy()).shape == ()


def test_spectral_norm_hook_normalizes():
    paddle.seed(0)
    lin = nn.Linear(6, 4)
    with np.errstate(all="ignore"):
        nn.utils.spectral_norm(lin, n_power_iterations=5)
    x = _t(_rand(2, 6, seed=10))
    lin(x)  # runs hook, updates u/v, recomputes weight
    s = np.linalg.svd(np.asarray(lin.weight._value),
                      compute_uv=False)[0]
    assert abs(s - 1.0) < 0.05
    lin(x).sum().backward()
    assert lin.weight_orig.grad is not None


def test_nn_quant_namespace():
    q = nn.quant
    out = q.add()(_t([1.0, 2.0]), _t([3.0, 4.0]))
    np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 6.0])
    assert q.QuantizedLinear is not None
    assert nn.spectral_norm is nn.utils.spectral_norm
