"""Elastic fault-injection payload (ref
test_fleet_launch_elastic.sh): two ranks train with auto-checkpointing;
on the FIRST attempt rank 1 dies by SIGKILL mid-run. The launcher's
elastic retry must relaunch the pod, and train_epoch_range must resume
from the latest snapshot instead of epoch 0."""

import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu.distributed import checkpoint as ckpt  # noqa: E402
from paddle_tpu.engine import Engine  # noqa: E402

out_dir = sys.argv[1]
rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
kill_epoch = 2
max_epoch = 6

attempt_marker = os.path.join(out_dir, f"attempt_r{rank}")
attempt = 1
if os.path.exists(attempt_marker):
    attempt = int(open(attempt_marker).read()) + 1
with open(attempt_marker, "w") as f:
    f.write(str(attempt))

paddle.seed(7 + rank)
model = nn.Linear(8, 4)
opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                parameters=model.parameters())
eng = Engine(model, opt, lambda out, y: ((out - y) ** 2).mean())
rng = np.random.RandomState(rank)
x = rng.randn(16, 8).astype(np.float32)
y = rng.randn(16, 4).astype(np.float32)

log = open(os.path.join(out_dir, f"epochs_r{rank}.log"), "a")
ckpt_dir = os.path.join(out_dir, f"ckpt_r{rank}")
for epoch in ckpt.train_epoch_range(max_epoch, ckpt_dir, eng,
                                    save_interval=1):
    if attempt == 1 and rank == 1 and epoch == kill_epoch:
        # ungraceful death mid-epoch: no cleanup, no checkpoint flush
        os.kill(os.getpid(), signal.SIGKILL)
    loss = float(np.asarray(eng.train_batch((x,), (y,)).item()))
    log.write(f"{attempt} {epoch} {loss:.6f}\n")
    log.flush()

log.close()
print(f"RANK {rank} DONE attempt={attempt}", flush=True)
