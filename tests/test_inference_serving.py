"""Serving-depth tests (VERDICT r3 item 7): predictor clone shares
weights, concurrent multi-threaded run over one exported artifact, pool
API, zero-copy input handles.

Ref parity: paddle/fluid/inference/api/analysis_predictor.h:82 (Clone
shared-weights contract), paddle_infer::services::PredictorPool,
paddle_infer::Tensor::ShareExternalData.
"""

import threading

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import InputSpec
import paddle_tpu.nn as nn


def _export(tmp_path, seed=5):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    model.eval()
    prefix = str(tmp_path / "served")
    paddle.jit.save(model, prefix,
                    input_spec=[InputSpec([4, 8], "float32")])
    return model, prefix


def test_clone_shares_weights_and_program(tmp_path):
    _, prefix = _export(tmp_path)
    pred = paddle.inference.create_predictor(
        paddle.inference.Config(prefix))
    clone = pred.clone()
    # the shared-weights contract is structural: same loaded layer
    # object, so N clones hold ONE copy of params + compiled program
    assert clone._layer is pred._layer
    assert clone.get_input_names() == pred.get_input_names()
    # handles must NOT be shared (per-thread mutable state)
    assert clone.get_input_handle(clone.get_input_names()[0]) is not \
        pred.get_input_handle(pred.get_input_names()[0])


def test_multithreaded_serving_over_one_artifact(tmp_path):
    """N threads, each with its own clone from a PredictorPool, hammer
    the same exported artifact concurrently; every result must equal the
    single-threaded reference for its batch."""
    model, prefix = _export(tmp_path)
    n_threads, n_reqs = 4, 12
    pool = paddle.inference.PredictorPool(
        paddle.inference.Config(prefix), n_threads)
    assert len(pool) == n_threads

    rng = np.random.RandomState(0)
    batches = [rng.randn(4, 8).astype(np.float32)
               for _ in range(n_threads * n_reqs)]
    expect = [model(Tensor(b)).numpy() for b in batches]

    results = [None] * len(batches)
    errors = []
    start = threading.Barrier(n_threads)

    def worker(tid):
        try:
            p = pool.retrieve(tid)
            h_in = p.get_input_handle(p.get_input_names()[0])
            start.wait()
            for r in range(n_reqs):
                i = tid * n_reqs + r
                h_in.copy_from_cpu(batches[i])
                assert p.run()
                results[i] = p.get_output_handle(
                    p.get_output_names()[0]).copy_to_cpu()
        except Exception as e:  # noqa: BLE001 — surface in main thread
            errors.append((tid, e))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for got, exp in zip(results, expect):
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)


def test_share_external_data_and_shrink(tmp_path):
    import jax

    _, prefix = _export(tmp_path)
    pred = paddle.inference.create_predictor(
        paddle.inference.Config(prefix))
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    dev_x = jax.device_put(x)  # caller-owned device buffer
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.share_external_data(dev_x)
    assert h._value is dev_x  # no copy for device-resident input
    pred.run()
    out1 = pred.get_output_handle(
        pred.get_output_names()[0]).copy_to_cpu()

    h.copy_from_cpu(x)
    pred.run()
    out2 = pred.get_output_handle(
        pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)

    pred.try_shrink_memory()
    assert pred.get_output_names() == []
