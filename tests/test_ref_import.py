"""Reference-artifact import (VERDICT r4 item 10).

The writer side of these tests re-implements the REFERENCE's binary
formats from its sources (lod_tensor.cc:244 SerializeToStream,
tensor_util.cc:774 TensorToStream, framework.proto field numbers,
io.py:408 sorted-by-name combined order) so the reader is checked
against an independent encoding, not against itself.
"""

import struct

import numpy as np
import pytest

from paddle_tpu import inference

_DT_ENUM = {np.dtype(np.float32): 5, np.dtype(np.int64): 3,
            np.dtype(np.float64): 6, np.dtype(np.int32): 2}


def _varint(v):
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _field(num, wire, payload):
    key = _varint((num << 3) | wire)
    if wire == 2:
        return key + _varint(len(payload)) + payload
    return key + payload


def _tensor_desc(arr):
    msg = _field(1, 0, _varint(_DT_ENUM[arr.dtype]))
    for d in arr.shape:
        msg += _field(2, 0, _varint(d))
    return msg


def _serialize_lod_tensor(arr, lod_levels=0):
    out = struct.pack("<I", 0)                    # LoDTensor version
    out += struct.pack("<Q", lod_levels)
    for _ in range(lod_levels):
        offs = np.asarray([0, 2], np.uint64)      # dummy level
        out += struct.pack("<Q", offs.nbytes) + offs.tobytes()
    out += struct.pack("<I", 0)                   # tensor version
    desc = _tensor_desc(arr)
    out += struct.pack("<i", len(desc)) + desc
    out += np.ascontiguousarray(arr).tobytes()
    return out


def _var_desc(name, arr, persistable=True):
    tensor = _tensor_desc(arr)
    lod_desc = _field(1, 2, tensor)               # LoDTensorDesc.tensor
    vtype = _field(1, 0, _varint(7))              # VarType.type=LOD_TENSOR
    vtype += _field(3, 2, lod_desc)               # VarType.lod_tensor
    msg = _field(1, 2, name.encode())
    msg += _field(2, 2, vtype)
    if persistable:
        msg += _field(3, 0, _varint(1))
    return msg


def _program_desc(named_arrays, extra_nonpersistable=()):
    block = _field(1, 0, _varint(0)) + _field(2, 0, _varint(0))
    for name, arr in named_arrays:
        block += _field(3, 2, _var_desc(name, arr))
    for name, arr in extra_nonpersistable:
        block += _field(3, 2, _var_desc(name, arr, persistable=False))
    return _field(1, 2, block)                    # ProgramDesc.blocks[0]


def _write_artifacts(tmp_path, named, prefix="model"):
    named = list(named)
    pdmodel = tmp_path / f"{prefix}.pdmodel"
    pdiparams = tmp_path / f"{prefix}.pdiparams"
    pdmodel.write_bytes(_program_desc(
        named, extra_nonpersistable=[("x", np.zeros((1, 4), np.float32))]))
    with open(pdiparams, "wb") as f:
        for name, arr in sorted(named):           # io.py:408 sorted order
            f.write(_serialize_lod_tensor(arr))
    return str(tmp_path / prefix)


def test_load_inference_params_roundtrip(tmp_path):
    rs = np.random.RandomState(0)
    named = [
        ("fc_0.w_0", rs.randn(8, 16).astype(np.float32)),
        ("fc_0.b_0", rs.randn(16).astype(np.float32)),
        ("emb.w_0", rs.randint(-5, 5, (32, 8)).astype(np.int64)
         .astype(np.float32)),
        ("scale", rs.randn(1).astype(np.float32)),
    ]
    prefix = _write_artifacts(tmp_path, named)
    got = inference.load_inference_params(prefix)
    assert set(got) == {n for n, _ in named}
    for name, arr in named:
        np.testing.assert_array_equal(got[name], arr)
        assert got[name].dtype == arr.dtype


def test_lod_levels_and_int64(tmp_path):
    arr = np.arange(12, dtype=np.int64).reshape(3, 4)
    path = tmp_path / "t.bin"
    path.write_bytes(_serialize_lod_tensor(arr, lod_levels=1))
    (got,) = inference.read_tensors(str(path))
    np.testing.assert_array_equal(got, arr)


def test_program_persistables_skips_feed_fetch_and_nonpersistable(tmp_path):
    named = [("w", np.zeros((2, 2), np.float32))]
    prefix = _write_artifacts(tmp_path, named)
    pers = inference.read_program_persistables(prefix + ".pdmodel")
    assert set(pers) == {"w"}
    assert pers["w"] == ([2, 2], np.dtype(np.float32))


def test_mismatched_artifacts_raise(tmp_path):
    named = [("a", np.zeros((2, 3), np.float32)),
             ("b", np.zeros((4,), np.float32))]
    prefix = _write_artifacts(tmp_path, named)
    # count mismatch
    with open(prefix + ".pdiparams", "wb") as f:
        f.write(_serialize_lod_tensor(np.zeros((2, 3), np.float32)))
    with pytest.raises(ValueError, match="declares 2 persistables"):
        inference.load_inference_params(prefix)
    # shape mismatch
    with open(prefix + ".pdiparams", "wb") as f:
        f.write(_serialize_lod_tensor(np.zeros((9, 9), np.float32)))
        f.write(_serialize_lod_tensor(np.zeros((4,), np.float32)))
    with pytest.raises(ValueError, match="shape mismatch"):
        inference.load_inference_params(prefix)


def test_loaded_weights_drive_a_model(tmp_path):
    """End-to-end migration: imported reference weights populate an
    equivalent paddle_tpu model and produce the expected output."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    rs = np.random.RandomState(1)
    w = rs.randn(4, 3).astype(np.float32)
    b = rs.randn(3).astype(np.float32)
    prefix = _write_artifacts(
        tmp_path, [("linear_0.w_0", w), ("linear_0.b_0", b)])
    params = inference.load_inference_params(prefix)

    lin = nn.Linear(4, 3)
    lin.weight.set_value(params["linear_0.w_0"])
    lin.bias.set_value(params["linear_0.b_0"])
    x = rs.randn(2, 4).astype(np.float32)
    got = np.asarray(lin(paddle.to_tensor(x)).numpy())
    np.testing.assert_allclose(got, x @ w + b, rtol=1e-5)
