"""Gang supervision tier-1 suite (ISSUE 14): collective deadlines,
typed retriable peer errors, the gang commit barrier, and the
GangSupervisor state machine — all in-process or with trivial non-jax
child processes, so every scenario the slow fork tests
(test_gang_slow.py) certify with real SIGKILLs has a fast equivalent
here."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from paddle_tpu.distributed import preempt
from paddle_tpu.distributed.checkpoint import GangCheckpointManager
from paddle_tpu.distributed.gang import (
    CollectiveTimeoutError, GangSupervisor, GangWorker, PeerGoneError,
    allreduce_host, barrier_host, call_with_deadline, deadline_guard,
    terminate_all, _free_ports)
from paddle_tpu.distributed.p2p import _Mailbox
from paddle_tpu.framework import faults, monitor
from paddle_tpu.framework.errors import (ExecutionTimeoutError,
                                         UnavailableError)


def _fake_env(rank, endpoints):
    return types.SimpleNamespace(rank=rank,
                                 world_size=len(endpoints),
                                 current_endpoint=endpoints[rank],
                                 trainer_endpoints=endpoints)


@pytest.fixture()
def boxes():
    """Two live in-process mailboxes wired to each other (ranks 0/1)."""
    eps = ["127.0.0.1:%d" % p for p in _free_ports(2)]
    pair = [_Mailbox(_fake_env(0, eps)), _Mailbox(_fake_env(1, eps))]
    yield pair
    for b in pair:
        b._tcp.shutdown()
        b._tcp.server_close()


# ---------------------------------------------------------------------------
# typed deadline errors
# ---------------------------------------------------------------------------


def test_recv_deadline_raises_typed_peer_gone(boxes):
    """Satellite 1: a recv from a gone peer raises PeerGoneError naming
    the src rank AND the deadline — never an anonymous hang."""
    t0 = time.monotonic()
    with pytest.raises(PeerGoneError) as ei:
        boxes[0].recv(1, timeout=0.2)
    assert time.monotonic() - t0 < 5.0
    assert "rank 1" in str(ei.value)
    assert "deadline" in str(ei.value)
    assert ei.value.retriable is True
    assert isinstance(ei.value, UnavailableError)


def test_collective_timeout_error_is_typed_retriable():
    """An injected delay past the per-call deadline surfaces as
    CollectiveTimeoutError (an ExecutionTimeoutError, retriable)."""
    before = monitor.stat_get("gang.collective_timeouts")
    with faults.ChaosSchedule("dist.allreduce@1:delay:0.2") as ch:
        with pytest.raises(CollectiveTimeoutError) as ei:
            deadline_guard("dist.allreduce", 0.05)
        ch.verify()
    assert ei.value.retriable is True
    assert isinstance(ei.value, ExecutionTimeoutError)
    assert monitor.stat_get("gang.collective_timeouts") == before + 1


def test_deadline_guard_disabled_and_remaining():
    assert deadline_guard("dist.allreduce", 0) is None
    left = deadline_guard("dist.allreduce", 5.0)
    assert 0 < left <= 5.0


def test_call_with_deadline_inline_result_error_and_timeout():
    assert call_with_deadline(lambda: 7, None, "x") == 7
    assert call_with_deadline(lambda: 7, 1.0, "x") == 7
    with pytest.raises(ValueError):
        call_with_deadline(lambda: (_ for _ in ()).throw(ValueError("b")),
                           1.0, "x")
    ev = threading.Event()
    with pytest.raises(CollectiveTimeoutError):
        call_with_deadline(ev.wait, 0.05, "stuck-op")
    ev.set()


def test_connect_retry_backoff_is_jittered_exponential(monkeypatch):
    """Satellite 1: reconnects back off exponentially WITH jitter so a
    restarted gang's survivors don't thundering-herd rank 0."""
    slept = []

    def _record(dt):
        slept.append(dt)
        if len(slept) >= 4:
            raise InterruptedError  # stop the retry loop

    monkeypatch.setattr(time, "sleep", _record)
    port = _free_ports(1)[0]  # nothing listens here
    with pytest.raises(InterruptedError):
        _Mailbox._connect_with_retry("127.0.0.1", port, deadline_s=30.0)
    for i, dt in enumerate(slept):
        base = 0.05 * 2 ** i
        assert 0.5 * base <= dt <= 1.5 * base, (i, dt)
    assert len(set(slept)) >= 2  # jittered, not a fixed ladder


# ---------------------------------------------------------------------------
# host collectives over the mailbox
# ---------------------------------------------------------------------------


def test_allreduce_host_matches_numpy_bitwise(boxes):
    a0 = np.arange(6, dtype=np.float64).reshape(2, 3) * 0.3
    a1 = np.linspace(-1, 1, 6).reshape(2, 3)
    for op, ref in [("sum", a0 + a1), ("mean", (a0 + a1) / 2.0),
                    ("max", np.maximum(a0, a1)),
                    ("min", np.minimum(a0, a1))]:
        out = [None, None]

        def _run(r, a, op=op):
            out[r] = allreduce_host(a, op, rank=r, world=2,
                                    deadline_s=10.0, box=boxes[r])

        ts = [threading.Thread(target=_run, args=(r, a))
              for r, a in ((0, a0), (1, a1))]
        [t.start() for t in ts]
        [t.join(10.0) for t in ts]
        np.testing.assert_array_equal(out[0], ref)
        np.testing.assert_array_equal(out[0], out[1])  # bitwise agree


def test_barrier_deadline_unblocks_every_live_rank(boxes):
    """Satellite 3 (dead-peer mode): a 3-rank barrier with rank 2
    missing must raise a typed error on BOTH live ranks within the
    deadline — no rank left blocked inside the collective."""
    errs = {}

    def _run(r):
        try:
            barrier_host(rank=r, world=3, deadline_s=0.4, box=boxes[r])
        except (CollectiveTimeoutError, PeerGoneError) as e:
            errs[r] = e

    ts = [threading.Thread(target=_run, args=(r,)) for r in (0, 1)]
    t0 = time.monotonic()
    [t.start() for t in ts]
    [t.join(8.0) for t in ts]
    assert not any(t.is_alive() for t in ts), "a rank is still blocked"
    assert time.monotonic() - t0 < 8.0
    assert set(errs) == {0, 1}
    assert all(e.retriable for e in errs.values())


def test_barrier_injected_delay_past_flag_raises_on_every_rank(boxes):
    """Satellite 3 (injected mode): dist.barrier@N:delay past
    FLAGS_dist_timeout_s raises CollectiveTimeoutError on every rank
    that hits it — deterministic, no transport involved."""
    from paddle_tpu.framework import flags as _flags

    prev = _flags.flag("FLAGS_dist_timeout_s")
    _flags.set_flags({"FLAGS_dist_timeout_s": 0.05})
    try:
        errs = {}
        with faults.ChaosSchedule("dist.barrier@1:delay:0.2",
                                  "dist.barrier@2:delay:0.2") as ch:
            for r in (0, 1):
                with pytest.raises(CollectiveTimeoutError) as ei:
                    barrier_host(rank=r, world=2, box=boxes[r])
                errs[r] = ei.value
            ch.verify()
        assert all(e.retriable for e in errs.values())
    finally:
        _flags.set_flags({"FLAGS_dist_timeout_s": prev})


def test_allreduce_fault_is_retriable_at_step_boundary():
    """Satellite 3: a fault-injected dist.allreduce surfaces as a
    retriable error AT the step boundary; retrying the step yields a
    bitwise-identical trajectory to the un-faulted run."""

    def train():
        w = np.linspace(0.0, 1.0, 4)
        for step in range(4):
            for attempt in range(3):
                try:
                    g = allreduce_host(w * 0.25 + step, "sum",
                                       rank=0, world=1,
                                       deadline_s=0.05)
                    break
                except CollectiveTimeoutError as e:
                    assert e.retriable  # retry the whole step
            else:
                raise AssertionError("step never succeeded")
            w = w - 0.1 * g
        return w

    clean = train()
    with faults.ChaosSchedule("dist.allreduce@2:delay:0.2") as ch:
        faulted = train()
        ch.verify()
    np.testing.assert_array_equal(clean, faulted)


# ---------------------------------------------------------------------------
# gang worker heartbeats
# ---------------------------------------------------------------------------


def test_gang_worker_beat_writes_watermark_and_drop_site(tmp_path):
    try:
        gw = GangWorker(gang_dir=str(tmp_path), rank=0)
        beat = tmp_path / "rank-0.beat"
        with faults.ChaosSchedule("gang.heartbeat@1:drop") as ch:
            gw.beat(step=3)   # dropped: the supervisor sees a stall
            assert not beat.exists()
            gw.beat(step=4)
            ch.verify()
        rec = json.loads(beat.read_text())
        assert rec["step"] == 4 and rec["node"] == "rank-0"
    finally:
        preempt.clear()


def test_gang_worker_deregisters_on_preemption(tmp_path):
    try:
        gw = GangWorker(gang_dir=str(tmp_path), rank=0)
        gw.beat(step=1)
        assert (tmp_path / "rank-0.beat").exists()
        preempt.request(reason="test")
        assert not (tmp_path / "rank-0.beat").exists()
    finally:
        preempt.clear()


# ---------------------------------------------------------------------------
# coordinated teardown
# ---------------------------------------------------------------------------


def test_terminate_all_sigkills_sigterm_ignorer_and_reaps():
    """Satellite 2: a child that ignores SIGTERM is SIGKILLed within the
    grace window and reaped — no zombie outlives the pod."""
    p = subprocess.Popen([
        sys.executable, "-c",
        "import signal, time; "
        "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
        "print('armed', flush=True); time.sleep(600)"],
        stdout=subprocess.PIPE)
    assert p.stdout.readline().strip() == b"armed"
    t0 = time.monotonic()
    terminate_all([p], grace=0.5)
    assert time.monotonic() - t0 < 10.0
    assert p.returncode == -signal.SIGKILL
    # reaped: waitpid has nothing left for this pid (no zombie)
    with pytest.raises(ChildProcessError):
        os.waitpid(p.pid, os.WNOHANG)


# ---------------------------------------------------------------------------
# gang commit barrier + globally consistent resume
# ---------------------------------------------------------------------------


def _both_save(mgrs, step, states):
    errs = []

    def _s(m, st):
        try:
            m.save(step, st)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=_s, args=(m, st))
          for m, st in zip(mgrs, states)]
    [t.start() for t in ts]
    [t.join(20.0) for t in ts]
    assert not errs, errs


def test_commit_barrier_makes_step_globally_readable(tmp_path):
    mgrs = [GangCheckpointManager(str(tmp_path), r, 2,
                                  barrier_timeout_s=10.0)
            for r in (0, 1)]
    states = [{"w": np.full(4, float(r + 1))} for r in (0, 1)]
    before = monitor.stat_get("gang.commits")
    _both_save(mgrs, 5, states)
    assert monitor.stat_get("gang.commits") == before + 2
    for m in mgrs:
        assert m.latest_committed_step() == 5
    marker = mgrs[0].marker(5)
    assert marker["world"] == 2 and set(marker["digests"]) == {"0", "1"}


def test_commit_barrier_times_out_when_a_rank_never_writes(tmp_path):
    """A rank dying between its local save and the barrier leaves the
    step UNCOMMITTED for everyone (rank 1 never saves here)."""
    m0 = GangCheckpointManager(str(tmp_path), 0, 2,
                               barrier_timeout_s=0.3)
    with pytest.raises(CollectiveTimeoutError) as ei:
        m0.save(2, {"w": np.ones(3)})
    assert ei.value.retriable
    assert m0.latest_committed_step() is None  # no GANG marker
    assert m0.local.is_readable(2)  # the local shard itself is fine


def test_restore_uses_newest_globally_committed_step(tmp_path):
    """Rank 1 has a NEWER local-only step (died pre-barrier): resume
    must come from the newest step the whole gang committed."""
    mgrs = [GangCheckpointManager(str(tmp_path), r, 2,
                                  barrier_timeout_s=10.0)
            for r in (0, 1)]
    committed = [{"w": np.arange(4) * 1.0}, {"w": np.arange(4) * 2.0}]
    _both_save(mgrs, 3, committed)
    # rank 1 gets further alone, then dies before the barrier
    mgrs[1].local.save(4, {"w": np.arange(4) * 9.0})
    mgrs[1]._write_json(mgrs[1]._rank_marker(4, 1),
                        {"rank": 1, "digest": "dead", "ts": 0})
    for r in (0, 1):
        step, st = mgrs[r].restore({"w": np.zeros(4)})
        assert step == 3
        np.testing.assert_array_equal(st["w"], committed[r]["w"])
    before = monitor.stat_get("gang.restores")
    mgrs[0].restore({"w": np.zeros(4)})
    assert monitor.stat_get("gang.restores") == before + 1


def test_restore_remaps_ranks_onto_smaller_writer_world(tmp_path):
    mgrs = [GangCheckpointManager(str(tmp_path), r, 2,
                                  barrier_timeout_s=10.0)
            for r in (0, 1)]
    _both_save(mgrs, 1, [{"w": np.full(2, 10.0)}, {"w": np.full(2, 20.0)}])
    # the world re-formed to 3 ranks: rank 2 maps onto writer 2 % 2 = 0
    m2 = GangCheckpointManager(str(tmp_path), 2, 3)
    step, st = m2.restore({"w": np.zeros(2)})
    assert step == 1
    np.testing.assert_array_equal(st["w"], np.full(2, 10.0))


def test_restore_digest_mismatch_is_detected(tmp_path):
    mgrs = [GangCheckpointManager(str(tmp_path), r, 2,
                                  barrier_timeout_s=10.0)
            for r in (0, 1)]
    _both_save(mgrs, 7, [{"w": np.ones(3)}, {"w": np.ones(3) * 2}])
    marker = mgrs[0].marker(7)
    marker["digests"]["0"] = "0" * 64  # bytes-on-disk vs commit mismatch
    mgrs[0]._write_json(mgrs[0]._gang_marker(7), marker)
    with pytest.raises(ValueError, match="digest mismatch"):
        mgrs[0].restore({"w": np.zeros(3)})


# ---------------------------------------------------------------------------
# supervisor state machine (children are plain python -c, no jax import)
# ---------------------------------------------------------------------------

# a child that beats its slot's heartbeat+step watermark like a real
# GangWorker, then follows a per-test script
_BEATER = r"""
import json, os, sys, time
slot = os.environ["PADDLE_GANG_SLOT"]
gang = os.environ["PADDLE_GANG_DIR"]
attempt = int(os.environ.get("PADDLE_GANG_ATTEMPT", "1"))
def beat(step):
    rec = {"node": "rank-" + slot, "ts": time.time(), "step": step}
    tmp = os.path.join(gang, "rank-" + slot + ".beat.tmp")
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, os.path.join(gang, "rank-" + slot + ".beat"))
"""


def _sup(tmp_path, script, nranks=2, **kw):
    import io

    kw.setdefault("max_restarts", 2)
    kw.setdefault("hang_secs", 0.0)
    kw.setdefault("grace_s", 2.0)
    kw.setdefault("poll_interval", 0.05)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("backoff_max_s", 0.02)
    kw.setdefault("stderr", io.StringIO())
    return GangSupervisor([sys.executable, "-c", _BEATER + script],
                          nranks, gang_dir=str(tmp_path / "gang"), **kw)


def test_supervisor_restart_then_success(tmp_path):
    """Rank 1 dies on attempt 1; the WHOLE gang is torn down, restarted
    with backoff, and attempt 2 completes — exit 0, one restart."""
    before = monitor.stat_get("gang.restarts")
    sup = _sup(tmp_path, """
beat(0)
if slot == "1" and attempt == 1:
    sys.exit(9)
time.sleep(0.4)  # outlive the victim: prove peers get torn down too
beat(1)
""")
    assert sup.run() == 0
    assert sup.restarts == 1 and sup.generation == 2
    assert monitor.stat_get("gang.restarts") == before + 1
    err = sup.stderr.getvalue()
    assert "exited with code 9; terminating the pod" in err
    assert "elastic restart 1/2 after exit code 9" in err


def test_supervisor_budget_exhaustion_propagates_code(tmp_path):
    sup = _sup(tmp_path, """
beat(0)
if slot == "1":
    sys.exit(7)
time.sleep(5)
""", max_restarts=1)
    assert sup.run() == 7
    assert sup.restarts == 1
    assert "restart budget exhausted" in sup.stderr.getvalue()


def test_supervisor_hang_detection_via_step_watermark(tmp_path):
    """A rank that keeps BEATING but stops advancing its step watermark
    is hung, not healthy: the supervisor restarts the gang."""
    sup = _sup(tmp_path, """
if attempt > 1:
    beat(0); sys.exit(0)
step = 0
for i in range(200):
    beat(step)            # liveness stays fresh ...
    if not (slot == "1" and i >= 3):
        step += 1         # ... but rank 1's step watermark freezes
    time.sleep(0.05)
""", hang_secs=0.6, max_restarts=2)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert "stalled" in sup.stderr.getvalue()


def test_supervisor_quarantines_flaky_slot_and_shrinks_world(tmp_path):
    """A slot that keeps killing the gang is quarantined; the world
    re-forms WITHOUT it (never below min_np) and completes."""
    before = monitor.stat_get("gang.quarantined")
    sup = _sup(tmp_path, """
beat(0)
if os.environ["PADDLE_TRAINERS_NUM"] == "1":
    sys.exit(0)           # the re-formed single-rank world completes
if slot == "1":
    sys.exit(3)           # flaky on every attempt
time.sleep(5)
""", min_np=1, max_restarts=4, quarantine_after=2)
    assert sup.run() == 0
    assert sup.quarantined == {1}
    assert sup.world_size() == 1
    assert monitor.stat_get("gang.quarantined") == before + 1
    assert "quarantined" in sup.stderr.getvalue()


def test_supervisor_membership_verdict_triggers_reformation(tmp_path):
    """A rank deregistering (preemption path) is a membership change:
    the ElasticManager verdict re-forms the gang even though every
    child process is still alive."""
    sup = _sup(tmp_path, """
if attempt > 1:
    beat(0); sys.exit(0)
for i in range(200):
    beat(i)
    if slot == "1" and i == 20:
        os.remove(os.path.join(gang, "rank-1.beat"))
        time.sleep(20)    # alive, but left the registry
    time.sleep(0.05)
""", max_restarts=2, hang_secs=0.0)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert "membership changed" in sup.stderr.getvalue()


def test_supervisor_gang_restart_site_fires(tmp_path):
    with faults.ChaosSchedule("gang.restart@1:delay:0.01") as ch:
        sup = _sup(tmp_path, """
beat(0)
if slot == "0" and attempt == 1:
    sys.exit(2)
""")
        assert sup.run() == 0
        ch.verify()


def test_supervisor_min_np_unformable_raises(tmp_path):
    with pytest.raises(ValueError, match="min_np"):
        GangSupervisor(["true"], 2, gang_dir=str(tmp_path / "g"),
                       min_np=3)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_gang_metrics_in_snapshot_and_prometheus(tmp_path):
    from paddle_tpu.observe import export

    with faults.ChaosSchedule("dist.p2p_recv@1:delay:0.2"):
        eps = ["127.0.0.1:%d" % p for p in _free_ports(1)]
        box = _Mailbox(_fake_env(0, eps))
        with pytest.raises((CollectiveTimeoutError, PeerGoneError)):
            box.recv(0, timeout=0.05)
        box._tcp.shutdown()
        box._tcp.server_close()
    snap = export.snapshot()
    assert "gang" in snap
    assert snap["gang"]["collective_timeouts"] >= 1
    text = export.prometheus_text()
    for fam in ("paddle_gang_restarts_total",
                "paddle_gang_collective_timeouts_total",
                "paddle_gang_peer_gone_total",
                "paddle_gang_commits_total",
                "paddle_gang_restart_lost_seconds_total"):
        assert fam in text, fam


def test_gang_restart_time_folds_into_goodput_as_restart(tmp_path):
    from paddle_tpu.observe import export

    sup = _sup(tmp_path, """
beat(0)
if slot == "1" and attempt == 1:
    sys.exit(4)
""")
    assert sup.run() == 0
    g = export.goodput()
    assert g["categories_s"]["restart"] > 0.0  # restart time is lost time
