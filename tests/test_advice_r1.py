"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.engine import Engine


def test_compiled_sgd_weight_decay_matches_eager():
    """#1: weight_decay must survive the compiled apply_gradients_tree."""
    paddle.seed(7)
    layer_e = nn.Linear(4, 3)
    layer_c = nn.Linear(4, 3)
    # identical weights
    for (k, a), (_, b) in zip(layer_e.state_dict().items(),
                              layer_c.state_dict().items()):
        b.set_value(a.numpy())

    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 3).astype(np.float32)

    opt_e = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=layer_e.parameters(),
                                 weight_decay=0.5)
    out = layer_e(paddle.to_tensor(x))
    loss = F.mse_loss(out, paddle.to_tensor(y))
    loss.backward()
    opt_e.step()

    opt_c = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=layer_c.parameters(),
                                 weight_decay=0.5)
    eng = Engine(layer_c, opt_c, lambda o, t: F.mse_loss(o, t))
    eng.train_batch(x, y)
    eng.sync_to_layer()

    for (k, a), (_, b) in zip(layer_e.state_dict().items(),
                              layer_c.state_dict().items()):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_compiled_lr_multiplier_applied():
    """#1: per-param optimize_attr learning_rate multiplier in compiled."""
    paddle.seed(3)
    layer = nn.Linear(2, 2)
    layer.weight.optimize_attr["learning_rate"] = 0.0  # freeze via lr mult
    w0 = layer.weight.numpy().copy()
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=layer.parameters())
    eng = Engine(layer, opt, lambda o, t: F.mse_loss(o, t))
    x = np.ones((4, 2), np.float32)
    y = np.zeros((4, 2), np.float32)
    eng.train_batch(x, y)
    eng.sync_to_layer()
    np.testing.assert_allclose(layer.weight.numpy(), w0)
    # bias has lr_mult 1.0 and must have moved
    assert np.abs(layer.bias.numpy()).sum() > 0


def test_gradscaler_unscale_then_step_no_double_unscale():
    """#2: scaler.unscale_ -> clip -> scaler.step must not divide twice."""
    paddle.seed(0)
    layer = nn.Linear(3, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=layer.parameters())
    scaler = paddle.amp.GradScaler(enable=True, init_loss_scaling=128.0)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    loss = layer(x).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.unscale_(opt)
    g_after_unscale = np.asarray(layer.weight._grad).copy()
    scaler.step(opt)  # must NOT unscale again
    np.testing.assert_allclose(np.asarray(layer.weight._grad),
                               g_after_unscale)
    # true grad of sum(layer(x)) wrt w for x=1: 2.0 each
    np.testing.assert_allclose(g_after_unscale, 2.0, rtol=1e-6)
    # after update(), the flag resets: next cycle unscales again
    opt.clear_grad()
    loss2 = layer(x).sum()
    scaler.scale(loss2).backward()
    scaler.unscale_(opt)
    np.testing.assert_allclose(np.asarray(layer.weight._grad), 2.0,
                               rtol=1e-6)


def test_weighted_cross_entropy():
    """#3: F.cross_entropy(weight=...) must work and match manual calc."""
    logits = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    labels = np.array([0, 1, 2, 1, 0], np.int64)
    w = np.array([1.0, 2.0, 0.5], np.float32)
    out = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          weight=paddle.to_tensor(w))
    # manual reference
    ex = np.exp(logits - logits.max(-1, keepdims=True))
    logp = np.log(ex / ex.sum(-1, keepdims=True))
    per = -logp[np.arange(5), labels] * w[labels]
    expected = per.sum() / w[labels].sum()
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)


def test_diag_embed_offset_square():
    """#4: diag_embed with offset returns a square matrix."""
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    out = paddle.diag_embed(x, offset=1)
    assert tuple(out.shape) == (4, 4)
    np.testing.assert_allclose(
        out.numpy(),
        np.diag(np.array([1, 2, 3], np.float32), k=1))
    out2 = paddle.diag_embed(x, offset=-2)
    assert tuple(out2.shape) == (5, 5)
    np.testing.assert_allclose(
        out2.numpy(), np.diag(np.array([1, 2, 3], np.float32), k=-2))


def test_batch_norm_use_global_stats_in_training():
    """#5: use_global_stats=True during training uses running stats."""
    rm = paddle.to_tensor(np.array([10.0, -10.0], np.float32))
    rv = paddle.to_tensor(np.array([4.0, 4.0], np.float32))
    w = paddle.to_tensor(np.ones(2, np.float32))
    b = paddle.to_tensor(np.zeros(2, np.float32))
    x = np.random.RandomState(0).randn(6, 2).astype(np.float32)
    y = F.batch_norm(paddle.to_tensor(x), rm, rv, w, b, training=True,
                     use_global_stats=True, epsilon=1e-5)
    expected = (x - np.array([10.0, -10.0])) / np.sqrt(4.0 + 1e-5)
    np.testing.assert_allclose(y.numpy(), expected, rtol=1e-5, atol=1e-5)
    # running stats must NOT have been updated
    np.testing.assert_allclose(rm.numpy(), [10.0, -10.0])
    np.testing.assert_allclose(rv.numpy(), [4.0, 4.0])


def test_adamw_decoupled_decay_compiled_vs_eager():
    """#1 follow-on: AdamW decoupled decay identical eager vs compiled."""
    paddle.seed(11)
    le, lc = nn.Linear(3, 2), nn.Linear(3, 2)
    for (k, a), (_, b) in zip(le.state_dict().items(),
                              lc.state_dict().items()):
        b.set_value(a.numpy())
    x = np.random.RandomState(2).randn(4, 3).astype(np.float32)
    y = np.random.RandomState(3).randn(4, 2).astype(np.float32)

    oe = paddle.optimizer.AdamW(learning_rate=0.01,
                                parameters=le.parameters(),
                                weight_decay=0.1)
    loss = F.mse_loss(le(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward()
    oe.step()

    oc = paddle.optimizer.AdamW(learning_rate=0.01,
                                parameters=lc.parameters(),
                                weight_decay=0.1)
    eng = Engine(lc, oc, lambda o, t: F.mse_loss(o, t))
    eng.train_batch(x, y)
    eng.sync_to_layer()
    for (k, a), (_, b) in zip(le.state_dict().items(),
                              lc.state_dict().items()):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-5,
                                   atol=1e-6, err_msg=k)
