"""SelectedRows sparse embedding gradients.

Ref intent: paddle/fluid/framework/selected_rows.h + the SelectedRows
kernels of lookup_table_v2_op / sgd_op / adam_op (lazy_mode), and
unittests/test_adam_op.py lazy-mode cases: the sparse path must agree
with the dense path numerically.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.selected_rows import SelectedRows


def _make(vocab=50, dim=8, sparse=False, seed=0):
    paddle.seed(seed)
    return nn.Embedding(vocab, dim, sparse=sparse)


def test_sparse_backward_produces_selected_rows():
    emb = _make(sparse=True)
    ids = paddle.to_tensor(np.array([[1, 3], [3, 7]], np.int64))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight._grad
    assert isinstance(g, SelectedRows)
    assert g.height == 50
    assert sorted(np.asarray(g.rows).tolist()) == [1, 3, 3, 7]
    # densified sparse grad == dense-path grad
    emb_d = _make(sparse=False)
    emb_d.weight._value = emb.weight._value
    out_d = emb_d(ids)
    out_d.sum().backward()
    np.testing.assert_allclose(np.asarray(g.to_dense()),
                               np.asarray(emb_d.weight._grad), rtol=1e-6)


def test_sparse_grad_accumulates_across_backwards():
    emb = _make(sparse=True)
    ids1 = paddle.to_tensor(np.array([1, 2], np.int64))
    ids2 = paddle.to_tensor(np.array([2, 4], np.int64))
    emb(ids1).sum().backward()
    emb(ids2).sum().backward()
    g = emb.weight._grad
    assert isinstance(g, SelectedRows)
    dense = np.asarray(g.to_dense())
    assert dense[2].sum() == 2 * emb.weight.shape[1]  # hit twice
    assert dense[1].sum() == emb.weight.shape[1]


def test_padding_idx_rows_zero():
    emb = nn.Embedding(20, 4, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.array([0, 5], np.int64))
    emb(ids).sum().backward()
    dense = np.asarray(emb.weight._grad.to_dense())
    assert np.all(dense[0] == 0)
    assert np.all(dense[5] == 1)


def test_sgd_sparse_matches_dense():
    ids = np.array([[3, 9, 3]], np.int64)
    emb_s = _make(sparse=True, seed=7)
    emb_d = _make(sparse=False, seed=7)
    np.testing.assert_allclose(np.asarray(emb_s.weight._value),
                               np.asarray(emb_d.weight._value))
    for emb in (emb_s, emb_d):
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=emb.parameters())
        loss = (emb(paddle.to_tensor(ids)) ** 2).sum()
        loss.backward()
        opt.step()
    np.testing.assert_allclose(np.asarray(emb_s.weight._value),
                               np.asarray(emb_d.weight._value), rtol=1e-5)


def test_adam_lazy_sparse_first_step_matches_dense():
    ids = np.array([[2, 5]], np.int64)
    emb_s = _make(sparse=True, seed=3)
    emb_d = _make(sparse=False, seed=3)
    opt_s = paddle.optimizer.Adam(learning_rate=0.01, lazy_mode=True,
                                  parameters=emb_s.parameters())
    opt_d = paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=emb_d.parameters())
    for emb, opt in ((emb_s, opt_s), (emb_d, opt_d)):
        (emb(paddle.to_tensor(ids)) ** 2).sum().backward()
        opt.step()
        opt.clear_grad()
    # with zero-init moments the first lazy step equals the dense step
    np.testing.assert_allclose(np.asarray(emb_s.weight._value),
                               np.asarray(emb_d.weight._value),
                               rtol=1e-5, atol=1e-6)


def test_adam_lazy_trains():
    emb = _make(vocab=30, dim=4, sparse=True, seed=1)
    opt = paddle.optimizer.Adam(learning_rate=0.05, lazy_mode=True,
                                parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([1, 4, 4, 9], np.int64))
    losses = []
    for _ in range(25):
        loss = (emb(ids) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_adam_nonlazy_sparse_falls_back_dense():
    emb = _make(sparse=True, seed=2)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([6], np.int64))
    (emb(ids) ** 2).sum().backward()
    opt.step()  # densify fallback must not crash
    st = opt._accumulators[id(emb.weight)]
    assert st["moment1"].shape == tuple(emb.weight.shape)
