"""paddle.reader decorators + paddle.batch (ref reader/decorator.py,
batch.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import reader


def _r(n):
    return lambda: iter(range(n))


def test_batch():
    assert list(paddle.batch(_r(7), 3)()) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(_r(7), 3, drop_last=True)()) == \
        [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(ValueError):
        paddle.batch(_r(3), 0)


def test_cache_and_firstn():
    calls = []

    def src():
        calls.append(1)
        return iter(range(5))

    c = reader.cache(src)
    assert list(c()) == list(range(5))
    assert list(c()) == list(range(5))
    assert len(calls) == 1  # second run replays from memory
    assert list(reader.firstn(_r(10), 3)()) == [0, 1, 2]


def test_map_chain_compose():
    assert list(reader.map_readers(lambda a, b: a + b, _r(3), _r(3))()) \
        == [0, 2, 4]
    assert list(reader.chain(_r(2), _r(3))()) == [0, 1, 0, 1, 2]
    got = list(reader.compose(
        lambda: iter([(1, 2), (3, 4)]), lambda: iter([5, 6]))())
    assert got == [(1, 2, 5), (3, 4, 6)]
    with pytest.raises(ValueError):
        list(reader.compose(_r(2), _r(3))())
    assert len(list(reader.compose(_r(2), _r(3),
                                   check_alignment=False)())) == 2


def test_shuffle_buffered():
    np.random.seed(0)
    got = list(reader.shuffle(_r(20), 5)())
    assert sorted(got) == list(range(20))
    assert list(reader.buffered(_r(50), 4)()) == list(range(50))


def test_xmap_readers_ordered_and_unordered():
    got = list(reader.xmap_readers(lambda x: x * 2, _r(30), 4, 8,
                                   order=True)())
    assert got == [2 * i for i in range(30)]
    got = list(reader.xmap_readers(lambda x: x * 2, _r(30), 4, 8)())
    assert sorted(got) == [2 * i for i in range(30)]


def test_multiprocess_reader():
    got = list(reader.multiprocess_reader([_r(5), _r(5)])())
    assert sorted(got) == sorted(list(range(5)) * 2)


def test_onnx_export_gated():
    with pytest.raises(RuntimeError, match="jit.save"):
        paddle.onnx.export(None, "x")


def test_dataset_reader_api():
    """paddle.dataset.<name>.train()/test() return composable readers
    (ref dataset/mnist.py:98 surface) over the same synthetic-fallback
    sources as the Dataset classes."""
    r = paddle.batch(paddle.dataset.uci_housing.train(), 8)
    xb = next(iter(r()))
    assert len(xb) == 8 and xb[0][0].shape == (13,)
    img, label = next(iter(paddle.dataset.mnist.test()()))
    assert img.shape[-2:] == (28, 28)
    assert 0 <= int(label) < 10
    x, y = next(iter(paddle.dataset.cifar.train10()()))
    assert x.shape[0] == 3 and 0 <= int(y) < 10
