"""paddle.reader decorators + paddle.batch (ref reader/decorator.py,
batch.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import reader


def _r(n):
    return lambda: iter(range(n))


def test_batch():
    assert list(paddle.batch(_r(7), 3)()) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(_r(7), 3, drop_last=True)()) == \
        [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(ValueError):
        paddle.batch(_r(3), 0)


def test_cache_and_firstn():
    calls = []

    def src():
        calls.append(1)
        return iter(range(5))

    c = reader.cache(src)
    assert list(c()) == list(range(5))
    assert list(c()) == list(range(5))
    assert len(calls) == 1  # second run replays from memory
    assert list(reader.firstn(_r(10), 3)()) == [0, 1, 2]


def test_map_chain_compose():
    assert list(reader.map_readers(lambda a, b: a + b, _r(3), _r(3))()) \
        == [0, 2, 4]
    assert list(reader.chain(_r(2), _r(3))()) == [0, 1, 0, 1, 2]
    got = list(reader.compose(
        lambda: iter([(1, 2), (3, 4)]), lambda: iter([5, 6]))())
    assert got == [(1, 2, 5), (3, 4, 6)]
    with pytest.raises(ValueError):
        list(reader.compose(_r(2), _r(3))())
    assert len(list(reader.compose(_r(2), _r(3),
                                   check_alignment=False)())) == 2


def test_shuffle_buffered():
    np.random.seed(0)
    got = list(reader.shuffle(_r(20), 5)())
    assert sorted(got) == list(range(20))
    assert list(reader.buffered(_r(50), 4)()) == list(range(50))


def test_xmap_readers_ordered_and_unordered():
    got = list(reader.xmap_readers(lambda x: x * 2, _r(30), 4, 8,
                                   order=True)())
    assert got == [2 * i for i in range(30)]
    got = list(reader.xmap_readers(lambda x: x * 2, _r(30), 4, 8)())
    assert sorted(got) == [2 * i for i in range(30)]


def test_multiprocess_reader():
    got = list(reader.multiprocess_reader([_r(5), _r(5)])())
    assert sorted(got) == sorted(list(range(5)) * 2)


def test_onnx_export_gated():
    with pytest.raises(RuntimeError, match="jit.save"):
        paddle.onnx.export(None, "x")


def test_dataset_reader_api():
    """paddle.dataset.<name>.train()/test() return composable readers
    (ref dataset/mnist.py:98 surface) over the same synthetic-fallback
    sources as the Dataset classes."""
    r = paddle.batch(paddle.dataset.uci_housing.train(), 8)
    xb = next(iter(r()))
    assert len(xb) == 8 and xb[0][0].shape == (13,)
    img, label = next(iter(paddle.dataset.mnist.test()()))
    assert img.shape[-2:] == (28, 28)
    assert 0 <= int(label) < 10
    x, y = next(iter(paddle.dataset.cifar.train10()()))
    assert x.shape[0] == 3 and 0 <= int(y) < 10


# -- review-finding regressions (r4) ----------------------------------------

def _boom(n_ok):
    def src():
        yield from range(n_ok)
        raise RuntimeError("shard corrupt")
    return src


def test_reader_errors_propagate_not_truncate():
    with pytest.raises(RuntimeError, match="shard corrupt"):
        list(reader.buffered(_boom(3), 2)())
    with pytest.raises(RuntimeError, match="shard corrupt"):
        list(reader.multiprocess_reader([_boom(3)])())
    # source raising mid-stream
    with pytest.raises(RuntimeError, match="shard corrupt"):
        list(reader.xmap_readers(lambda x: x, _boom(3), 2, 4)())
    # mapper raising must not deadlock either
    def bad_map(x):
        raise ValueError("decode failed")
    with pytest.raises(ValueError, match="decode failed"):
        list(reader.xmap_readers(bad_map, _r(5), 2, 4)())


def test_cache_retry_does_not_duplicate():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            yield from range(3)
            raise RuntimeError("transient")
        yield from range(5)

    c = reader.cache(flaky)
    with pytest.raises(RuntimeError):
        list(c())
    assert list(c()) == list(range(5))  # no stale [0,1,2] prefix


def test_s2d_stem_odd_input_dims():
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.vision.models.resnet import (
        SpaceToDepthStem, fold_conv7_stem,
    )
    from paddle_tpu import nn
    import paddle_tpu as paddle

    paddle.seed(0)
    conv7 = nn.Conv2D(3, 8, 7, stride=2, padding=3, bias_attr=False)
    s2d = SpaceToDepthStem(3, 8)
    s2d.conv.weight._value = jnp.asarray(
        fold_conv7_stem(np.asarray(conv7.weight._value)))
    for hw in (33, 25):  # odd sizes crashed before the pad fix
        x = Tensor(np.random.RandomState(hw).randn(1, 3, hw, hw)
                   .astype(np.float32))
        np.testing.assert_allclose(s2d(x).numpy(), conv7(x).numpy(),
                                   rtol=1e-5, atol=1e-5)


def test_instance_group_norm_bf16_large_mean():
    """One-pass variance must not cancel at bf16: mean ~16, std ~0.1."""
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import apply
    from paddle_tpu.core.tensor import Tensor

    rng = np.random.RandomState(0)
    x = (16.0 + 0.1 * rng.randn(2, 4, 8, 8)).astype(np.float32)
    for op, attrs in (("instance_norm", {}),
                      ("group_norm", {"groups": 2})):
        got = apply(op, Tensor(jnp.asarray(x, jnp.bfloat16)), **attrs)
        got = got[0] if isinstance(got, tuple) else got
        out = np.asarray(got.numpy(), np.float32)
        # the cancellation bug made var==0 -> outputs scaled by
        # rsqrt(eps) ~ 316x; a healthy normalisation has unit-ish std.
        # (bf16 quantises the ±0.1 signal itself, so elementwise
        # comparison against f32 is meaningless in this regime.)
        assert 0.3 < out.std() < 3.0, (op, out.std())
        assert np.abs(out.mean()) < 0.2, (op, out.mean())
