"""DGC / ASP / LocalSGD on the COMPILED engine path.

Ref parity: fleet/meta_optimizers/{dgc_optimizer,asp_optimizer,
localsgd_optimizer}.py — the reference implements these as program
passes so they survive compilation; round-2 review found this repo ran
them only in eager mode. Each test proves the semantics inside the
jitted train step (and, for DGC's exchange, inside shard_map on the
8-device mesh).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.engine import Engine
from paddle_tpu.incubate import asp
from paddle_tpu.distributed.fleet.meta_optimizers.dgc import (
    DGCMomentumOptimizer, dgc_sparse_allreduce,
)


def _mse(out, y):
    return ((out - y) ** 2).mean()


def _batch(din=16, dout=8, n=8, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, din).astype(np.float32),
            rng.randn(n, dout).astype(np.float32))


def test_dgc_trains_through_engine():
    """DGC as a real Optimizer: Engine compiles its _rule; residual
    accumulators live in opt_state and carry across steps."""
    paddle.seed(50)
    m = nn.Linear(16, 8)
    opt = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                               parameters=m.parameters(),
                               rampup_begin_step=2, sparsity=(0.75,))
    eng = Engine(m, opt, _mse)
    x, y = _batch()
    losses = [float(np.asarray(eng.train_batch((x,), (y,)).item()))
              for _ in range(25)]
    assert losses[-1] < losses[2] * 0.8, losses
    # after compression begins, the residual accumulator holds unsent
    # mass inside the COMPILED opt_state
    v = eng.state.opt_state["weight"]["v"]
    assert float(jnp.abs(v).sum()) > 0.0
    t = eng.state.opt_state["weight"]["t"]
    assert int(t) == 25


def test_dgc_eager_matches_engine():
    """Same seed + data: the eager step() and the compiled engine path
    run the identical rule."""
    paddle.seed(51)
    m1 = nn.Linear(8, 4)
    paddle.seed(51)
    m2 = nn.Linear(8, 4)
    x, y = _batch(8, 4)

    o1 = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                              parameters=m1.parameters(),
                              rampup_begin_step=1, sparsity=(0.5,))
    o2 = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                              parameters=m2.parameters(),
                              rampup_begin_step=1, sparsity=(0.5,))
    eng = Engine(m2, o2, _mse)
    eager_losses, eng_losses = [], []
    for _ in range(6):
        loss = _mse(m1(Tensor(x)), Tensor(y))
        loss.backward()
        o1.step()
        o1.clear_grad()
        eager_losses.append(float(loss.numpy()))
        eng_losses.append(float(np.asarray(
            eng.train_batch((x,), (y,)).item())))
    np.testing.assert_allclose(eng_losses, eager_losses, rtol=1e-4)


def test_dgc_sparse_allreduce_on_mesh():
    """The exchange half inside shard_map over dp on the virtual mesh:
    each rank ships k (index, value) pairs; the summed sparse update
    matches a numpy reference of per-rank top-k with error feedback."""
    from jax.sharding import Mesh, PartitionSpec as P

    ndev = min(4, jax.device_count())
    if ndev < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    rng = np.random.RandomState(0)
    g = rng.randn(ndev, 16).astype(np.float32)   # per-rank local grads
    u0 = np.zeros_like(g)
    v0 = np.zeros_like(g)
    k = 3

    def local(gg, uu, vv):
        upd, u, v = dgc_sparse_allreduce(gg[0], uu[0], vv[0], k=k,
                                         momentum=0.9, axis_name="dp")
        return upd, u[None], v[None]

    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(P("dp"), P("dp"), P("dp")),
                       out_specs=(P(), P("dp"), P("dp")),
                       check_vma=False)
    update, u1, v1 = jax.jit(fn)(g, u0, v0)

    # numpy reference
    want = np.zeros(16, np.float32)
    wu, wv = [], []
    for r in range(ndev):
        u = 0.9 * u0[r] + g[r]
        v = v0[r] + u
        idx = np.argsort(-np.abs(v))[:k]
        sel = np.zeros(16, bool)
        sel[idx] = True
        want[sel] += v[sel]
        wu.append(np.where(sel, 0.0, u))
        wv.append(np.where(sel, 0.0, v))
    want /= ndev
    np.testing.assert_allclose(np.asarray(update), want, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(u1), np.stack(wu), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.stack(wv), rtol=1e-5)


def test_asp_masks_survive_engine_training():
    """round-2 weak #6: masks must be re-applied INSIDE the compiled
    step, not only by the eager wrapper."""
    paddle.seed(52)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    masks = asp.prune_model(model)
    assert masks
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=model.parameters())
    eng = Engine(model, opt, _mse)
    x, y = _batch()
    losses = [float(np.asarray(eng.train_batch((x,), (y,)).item()))
              for _ in range(6)]
    assert losses[-1] < losses[0]
    # compiled-state params keep the 2:4 pattern
    for name in masks:
        arr = np.asarray(eng.state.params[name])
        assert asp.check_sparsity(arr), name


def test_localsgd_single_collective(monkeypatch):
    """Averaging performs ONE process_allgather over the whole tree
    (round-2 weak #7: was one host round-trip per parameter)."""
    from paddle_tpu.distributed.fleet.meta_optimizers import localsgd

    paddle.seed(53)
    m = nn.Linear(8, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=m.parameters())
    opt = localsgd.LocalSGDOptimizer(inner, k_steps=1)

    calls = []

    def fake_allgather(tree):
        calls.append(tree)
        # simulate 2 processes: this rank's values + a zero replica
        return jax.tree.map(
            lambda a: jnp.stack([jnp.asarray(a),
                                 jnp.zeros_like(jnp.asarray(a))]), tree)

    monkeypatch.setattr(localsgd.jax, "process_count", lambda: 2)
    import jax.experimental.multihost_utils as mh
    monkeypatch.setattr(mh, "process_allgather", fake_allgather)

    before = {k: np.asarray(v._value)
              for k, v in m.state_dict().items()}
    opt.average_parameters()
    assert len(calls) == 1, "expected exactly one tree-wide collective"
    for k, v in m.state_dict().items():
        np.testing.assert_allclose(np.asarray(v._value), before[k] / 2,
                                   rtol=1e-6)


def test_sync_batch_norm_matches_global_bn():
    """sync_batch_norm inside shard_map over dp must equal plain BN on
    the concatenated global batch (ref sync_batch_norm_op.cu tests)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.core.op_registry import _REGISTRY

    ndev = min(4, jax.device_count())
    if ndev < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    rng = np.random.RandomState(0)
    x = rng.randn(ndev * 2, 3, 4, 4).astype(np.float32)
    scale = rng.rand(3).astype(np.float32) + 0.5
    bias = rng.randn(3).astype(np.float32)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)

    sbn = _REGISTRY["sync_batch_norm"].fn

    def local(xx):
        y, (m, v) = sbn(xx, jnp.asarray(scale), jnp.asarray(bias),
                        jnp.asarray(mean), jnp.asarray(var))
        return y, m, v

    fn = jax.shard_map(local, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=(P("dp"), P(), P()),
                       check_vma=False)
    y, m, v = jax.jit(fn)(x)

    bn = _REGISTRY["batch_norm"].fn
    want_y, (want_m, want_v) = bn(jnp.asarray(x), jnp.asarray(scale),
                                  jnp.asarray(bias), jnp.asarray(mean),
                                  jnp.asarray(var))
    np.testing.assert_allclose(np.asarray(y), np.asarray(want_y),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(m), np.asarray(want_m),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(want_v),
                               rtol=1e-4, atol=1e-6)
