"""LookAhead / ModelAverage / ExponentialMovingAverage equivalence tests.

Each wrapper is checked against an independent numpy hand-rolling of the
reference semantics (incubate/optimizer/lookahead.py:118,
average_accumulates_op.h:80-106, fluid/optimizer.py:3883), on both the
eager step() path and the compiled Engine path.
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.engine import Engine


def _sgd_quadratic(w0, lr, steps):
    """Hand-rolled SGD on loss = sum(w^2): returns list of param values
    AFTER each step (grad = 2w)."""
    w = w0.copy()
    traj = []
    for _ in range(steps):
        w = w - lr * 2.0 * w
        traj.append(w.copy())
    return traj


# -- LookAhead ---------------------------------------------------------------

def test_lookahead_eager_matches_handrolled():
    lr, alpha, k, steps = 0.1, 0.5, 3, 10
    w0 = np.array([5.0, -3.0], np.float32)

    # hand-rolled reference: fast SGD + every-k slow sync
    fast, slow = w0.copy(), w0.copy()
    for t in range(1, steps + 1):
        fast = fast - lr * 2.0 * fast
        if t % k == 0:
            slow = slow + alpha * (fast - slow)
            fast = slow.copy()

    w = paddle.core.Parameter(w0.copy())
    opt = optimizer.LookAhead(
        optimizer.SGD(learning_rate=lr, parameters=[w]), alpha=alpha, k=k)
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(w.numpy(), fast, rtol=1e-6)


def test_lookahead_engine_matches_eager():
    paddle.seed(7)
    lin = nn.Linear(4, 3)
    w0 = {k: np.asarray(v._value).copy()
          for k, v in lin.state_dict().items()}
    rng = np.random.RandomState(0)
    xs = [rng.randn(8, 4).astype(np.float32) for _ in range(7)]
    ys = [rng.randn(8, 3).astype(np.float32) for _ in range(7)]

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    opt = optimizer.LookAhead(
        optimizer.SGD(learning_rate=0.05, parameters=lin.parameters()),
        alpha=0.8, k=2)
    eng = Engine(lin, opt, loss_fn)
    for x, y in zip(xs, ys):
        eng.train_batch(x, y)

    # eager replay from the same init
    paddle.seed(7)
    lin2 = nn.Linear(4, 3)
    for k2, v in lin2.state_dict().items():
        v._value = paddle.core.Tensor(w0[k2])._value
    opt2 = optimizer.LookAhead(
        optimizer.SGD(learning_rate=0.05, parameters=lin2.parameters()),
        alpha=0.8, k=2)
    for x, y in zip(xs, ys):
        out = lin2(paddle.core.Tensor(x))
        loss = ((out - paddle.core.Tensor(y)) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()

    for name, v in lin2.state_dict().items():
        np.testing.assert_allclose(
            np.asarray(eng.state.params[name]), np.asarray(v._value),
            rtol=2e-5, atol=1e-6)


# -- ModelAverage ------------------------------------------------------------

def _modelaverage_ref(traj, rate, min_w, max_w):
    """Numpy hand-rolling of the average_accumulates rule over a
    parameter trajectory; returns the applied average after the last
    accumulation."""
    s1 = np.zeros_like(traj[0])
    s2 = np.zeros_like(traj[0])
    s3 = np.zeros_like(traj[0])
    n_acc = old = n_upd = 0
    for p in traj:
        n_upd += 1
        n_acc += 1
        s1 = s1 + p
        if n_acc >= min_w and n_acc >= min(max_w, n_upd * rate):
            s3 = s1 + s2
            s1, s2 = np.zeros_like(s1), np.zeros_like(s2)
            old, n_acc = n_acc, 0
    total = n_acc + old
    return (s1 + s2 + s3) / max(total, 1)


def test_modelaverage_standalone_matches_handrolled():
    lr, steps = 0.1, 9
    rate, min_w, max_w = 0.5, 2, 4
    w0 = np.array([4.0, -2.0], np.float32)
    traj = _sgd_quadratic(w0, lr, steps)
    want = _modelaverage_ref(traj, rate, min_w, max_w)

    w = paddle.core.Parameter(w0.copy())
    sgd = optimizer.SGD(learning_rate=lr, parameters=[w])
    ma = optimizer.ModelAverage(rate, parameters=[w],
                                min_average_window=min_w,
                                max_average_window=max_w)
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        sgd.step()
        ma.step()          # reference usage: accumulate after the update
        sgd.clear_grad()
    before = w.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(w.numpy(), want, rtol=1e-5)
    np.testing.assert_allclose(w.numpy(), before)  # restored


def test_modelaverage_engine_wrapper():
    paddle.seed(11)
    lin = nn.Linear(3, 2)
    rng = np.random.RandomState(1)
    xs = [rng.randn(6, 3).astype(np.float32) for _ in range(6)]
    ys = [rng.randn(6, 2).astype(np.float32) for _ in range(6)]

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    ma = optimizer.ModelAverage(
        1.0, min_average_window=2, max_average_window=3,
        inner_optimizer=optimizer.SGD(learning_rate=0.05,
                                      parameters=lin.parameters()))
    eng = Engine(lin, ma, loss_fn)
    traj = []
    for x, y in zip(xs, ys):
        eng.train_batch(x, y)
        traj.append(np.asarray(eng.state.params["weight"]).copy())

    want = _modelaverage_ref(traj, 1.0, 2, 3)
    raw = traj[-1]
    with ma.apply(engine=eng):
        np.testing.assert_allclose(
            np.asarray(eng.state.params["weight"]), want, rtol=1e-5)
        # write-through to the layer for eval
        np.testing.assert_allclose(
            np.asarray(lin.state_dict()["weight"]._value), want, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(eng.state.params["weight"]), raw)


# -- ExponentialMovingAverage ------------------------------------------------

def test_ema_matches_handrolled_bias_correction():
    lr, decay, steps = 0.1, 0.9, 6
    w0 = np.array([3.0, 1.0], np.float32)
    traj = _sgd_quadratic(w0, lr, steps)
    ema = np.zeros_like(w0)
    for p in traj:
        ema = decay * ema + (1 - decay) * p
    want = ema / (1 - decay ** steps)

    w = paddle.core.Parameter(w0.copy())
    sgd = optimizer.SGD(learning_rate=lr, parameters=[w])
    e = optimizer.ExponentialMovingAverage(decay, parameters=[w])
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        sgd.step()
        e.update()
        sgd.clear_grad()
    before = w.numpy().copy()
    with e.apply():
        np.testing.assert_allclose(w.numpy(), want, rtol=1e-5)
    np.testing.assert_allclose(w.numpy(), before)


def test_ema_thres_steps_schedule():
    # scheduled decay: d_t = min(decay, (1+t)/(10+t)), t = 0-based count
    lr, decay, steps = 0.1, 0.999, 5
    w0 = np.array([2.0], np.float32)
    traj = _sgd_quadratic(w0, lr, steps)
    ema, prod = np.zeros_like(w0), 1.0
    for t, p in enumerate(traj):
        d = min(decay, (1 + t) / (10 + t))
        ema = d * ema + (1 - d) * p
        prod *= d
    want = ema / (1 - prod)

    w = paddle.core.Parameter(w0.copy())
    sgd = optimizer.SGD(learning_rate=lr, parameters=[w])
    e = optimizer.ExponentialMovingAverage(decay, thres_steps=True,
                                           parameters=[w])
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        sgd.step()
        e.update()
        sgd.clear_grad()
    with e.apply():
        np.testing.assert_allclose(w.numpy(), want, rtol=1e-5)


def test_ema_engine_wrapper():
    paddle.seed(3)
    lin = nn.Linear(3, 2)
    rng = np.random.RandomState(2)
    xs = [rng.randn(5, 3).astype(np.float32) for _ in range(5)]
    ys = [rng.randn(5, 2).astype(np.float32) for _ in range(5)]

    def loss_fn(out, y):
        return ((out - y) ** 2).mean()

    decay = 0.8
    e = optimizer.ExponentialMovingAverage(
        decay, inner_optimizer=optimizer.SGD(
            learning_rate=0.05, parameters=lin.parameters()))
    eng = Engine(lin, e, loss_fn)
    traj = []
    for x, y in zip(xs, ys):
        eng.train_batch(x, y)
        traj.append(np.asarray(eng.state.params["bias"]).copy())

    ema = np.zeros_like(traj[0])
    for p in traj:
        ema = decay * ema + (1 - decay) * p
    want = ema / (1 - decay ** len(traj))
    raw = traj[-1]
    with e.apply(engine=eng):
        np.testing.assert_allclose(
            np.asarray(eng.state.params["bias"]), want, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(eng.state.params["bias"]), raw)


def test_wrapper_state_dict_roundtrip():
    w = paddle.core.Parameter(np.array([1.0, 2.0], np.float32))
    opt = optimizer.LookAhead(
        optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                           parameters=[w]), alpha=0.5, k=2)
    for _ in range(3):
        ((w * w).sum()).backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    assert any(k.endswith(".la_slow") for k in sd)
    assert any(k.endswith(".velocity") for k in sd)

    w2 = paddle.core.Parameter(np.array([1.0, 2.0], np.float32))
    opt2 = optimizer.LookAhead(
        optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                           parameters=[w2]), alpha=0.5, k=2)
    opt2.set_state_dict(sd)
    st = opt2._accumulators[id(w2)]
    np.testing.assert_allclose(np.asarray(st["la_slow"]),
                               np.asarray(opt._accumulators[id(w)]["la_slow"]))


def test_restore_engine_mismatch_raises_and_recovers():
    # review finding (r4): restore() without the engine apply() was given
    # must not silently discard the saved originals
    import pytest

    paddle.seed(5)
    lin = nn.Linear(2, 2)
    e = optimizer.ExponentialMovingAverage(
        0.9, inner_optimizer=optimizer.SGD(
            learning_rate=0.1, parameters=lin.parameters()))
    eng = Engine(lin, e, lambda out, y: ((out - y) ** 2).mean())
    x = np.ones((3, 2), np.float32)
    y = np.zeros((3, 2), np.float32)
    eng.train_batch(x, y)
    raw = np.asarray(eng.state.params["weight"]).copy()

    e._apply_swap(engine=eng)
    with pytest.raises(RuntimeError, match="restore"):
        e.restore()  # wrong: eager path has no accumulators
    e.restore(engine=eng)  # originals still held; correct call recovers
    np.testing.assert_allclose(np.asarray(eng.state.params["weight"]), raw)
