"""GPT autoregressive generation with KV cache.

Ref parity: paddlenlp GenerationMixin.generate (greedy/sampling) and
the decode caches of fused_multi_transformer — incremental decode must
produce EXACTLY the logits of a full forward pass, and greedy decoding
must equal the argmax chain over full re-forwarding.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _full_logits(m, ids):
    out = m(Tensor(jnp.asarray(ids, jnp.int32)))
    return np.asarray(out._value if hasattr(out, "_value") else out,
                      np.float32)


def test_cached_decode_matches_full_forward(gpt):
    """Prefill + per-token steps must reproduce the full-forward logits
    at every position (fp32 cache vs bf16 default would diverge; use
    f32 caches for the exactness check)."""
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (2, 10)).astype(np.int32)
    want = _full_logits(gpt, ids)           # [2, 10, V]

    caches = gpt.gpt.init_caches(2, 16, dtype=jnp.float32)
    h, caches = gpt.gpt(Tensor(jnp.asarray(ids[:, :4])),
                        Tensor(jnp.arange(4, dtype=jnp.int32)), caches)
    got_prefill = np.asarray(gpt.logits(h)._value, np.float32)
    np.testing.assert_allclose(got_prefill, want[:, :4], rtol=2e-3,
                               atol=2e-3)
    # token-by-token continuation
    for t in range(4, 10):
        h, caches = gpt.gpt(Tensor(jnp.asarray(ids[:, t:t + 1])),
                            Tensor(jnp.asarray([t], jnp.int32)), caches)
        got = np.asarray(gpt.logits(h)._value, np.float32)[:, 0]
        np.testing.assert_allclose(got, want[:, t], rtol=2e-3,
                                   atol=2e-3)


def test_greedy_generate_matches_full_reforward(gpt):
    """generate() greedy chain == argmax chain over full re-forwarding
    (the no-cache reference decoder)."""
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 97, (2, 5)).astype(np.int32)
    out = np.asarray(gpt.generate(Tensor(jnp.asarray(ids)),
                                  max_new_tokens=6)._value)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[:, :5], ids)

    ref = ids.copy()
    for _ in range(6):
        logits = _full_logits(gpt, ref)
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        ref = np.concatenate([ref, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, ref)


def test_generate_eos_padding(gpt):
    """After a sequence emits eos, the remainder is eos-padded and the
    output keeps its static shape."""
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 97, (1, 4)).astype(np.int32)
    # find the first greedy token and use IT as the eos id, forcing an
    # immediate stop for this sequence
    first = int(_full_logits(gpt, ids)[:, -1].argmax(-1)[0])
    out = np.asarray(gpt.generate(Tensor(jnp.asarray(ids)),
                                  max_new_tokens=5,
                                  eos_token_id=first)._value)
    assert out.shape == (1, 9)
    np.testing.assert_array_equal(out[0, 4:], first)


def test_sampling_respects_top_k(gpt):
    """top_k=1 sampling degenerates to greedy regardless of seed."""
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 97, (2, 5)).astype(np.int32)
    greedy = np.asarray(gpt.generate(Tensor(jnp.asarray(ids)),
                                     max_new_tokens=4)._value)
    for seed in (0, 7):
        sampled = np.asarray(gpt.generate(
            Tensor(jnp.asarray(ids)), max_new_tokens=4, do_sample=True,
            top_k=1, seed=seed)._value)
        np.testing.assert_array_equal(sampled, greedy)
