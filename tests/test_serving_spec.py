"""Fast decode (ISSUE 16): speculative decoding + the int8 weight path
in the unified SlotEngine step.

Tentpole teeth: speculative greedy decode is BITWISE identical to plain
greedy (every emitted token is an argmax over the same logits row the
plain engine would compute), self-draft acceptance is exactly 1.0, the
standalone rejection sampler reproduces the target distribution, the
verify step's bulk KV scatter writes the same pool rows the plain
engine's one-token steps write, and compile counters stay at one trace
per kind (`decode`/`draft`/`cow`) for an engine's whole life.

Satellites certified here: the `serving.draft` / `serving.verify` /
`serving.dequant` fault sites (a draft fault degrades the round to
plain decode — the slot survives with no lost or duplicated tokens),
quantized WeightVersion artifacts rolling out and bitwise rolling back
through the fleet, the `paddle_serving_spec_*` Prometheus family, and
the ``bench_serving.py --spec --smoke`` certification subprocess.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import observe, serving
from paddle_tpu.framework import faults
from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining
from paddle_tpu.ops import quant_ops
from paddle_tpu.quantization import (
    SCALE_SUFFIX, dequantize_state, is_quantized_state,
    quantize_state_int8,
)
from paddle_tpu.serving import positions_to_rows
from paddle_tpu.serving.engine import speculative_accept

REPO = Path(__file__).resolve().parent.parent
VOCAB = 97


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft_gpt():
    """A weaker, differently-shaped draft model over the same vocab —
    real rejection traffic for the draft/verify loop."""
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        1, VOCAB, (n,)).astype(np.int32)


def _drive(eng, prompt, max_new=6, snoop_first_logits=False, **gen):
    """Admit + step one request synchronously, mirroring `_loop`'s
    fail-all-on-step-error contract for deterministic fault tests."""
    fut = eng.submit(np.asarray(prompt, np.int32),
                     max_new_tokens=max_new, timeout=None, **gen)
    eng._admit()
    first = None
    while eng.active:
        try:
            eng._step()
        except Exception as e:  # noqa: BLE001 — _loop parity
            eng.metrics.inc("step_errors")
            eng._fail_all_active(e)
        if snoop_first_logits and first is None:
            for s in eng._slots:
                if s is not None and s.state == "decode" \
                        and s.next_logits is not None:
                    first = np.asarray(s.next_logits).copy()
    return fut.result(10), first


def _engine(gpt, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    e = serving.SlotEngine(gpt, **kw)
    e.warmup()
    return e


# ---------------------------------------------------------------------------
# tentpole: bitwise greedy parity, acceptance, compile-once
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_greedy_parity_self_draft(gpt, k):
    """Speculative greedy == plain greedy BITWISE for spec_len 1/2/4
    (self-draft), across short and longer-than-chunk prompts — and the
    whole run costs exactly one decode, one draft, and one CoW trace."""
    plain = _engine(gpt)
    spec = _engine(gpt, spec_len=k)
    cases = [(_prompt(3, 5), 7), (_prompt(50, 29), 6), (_prompt(9, 12), 9)]
    for p, n in cases:
        want, _ = _drive(plain, p, max_new=n)
        got, _ = _drive(spec, p, max_new=n)
        np.testing.assert_array_equal(got, want)
    assert spec.compile_counts == {"decode": 1, "draft": 1, "cow": 1}
    assert plain.compile_counts == {"decode": 1, "cow": 1}
    # self-draft: q == p, so every proposal survives accept/reject
    snap = spec.metrics.snapshot()["speculative"]
    assert snap["acceptance_rate"] == 1.0
    assert snap["drafted_tokens"] > 0
    assert snap["rejected_tokens"] == 0


def test_spec_greedy_parity_weak_draft(gpt, draft_gpt):
    """Bitwise parity holds for a REAL (weaker, differently-shaped)
    draft model too: rejections cost speed, never tokens."""
    plain = _engine(gpt)
    spec = _engine(gpt, spec_len=3, draft_model=draft_gpt)
    for seed in (21, 22, 23):
        p = _prompt(seed, 7)
        want, _ = _drive(plain, p, max_new=8)
        got, _ = _drive(spec, p, max_new=8)
        np.testing.assert_array_equal(got, want)
    snap = spec.metrics.snapshot()["speculative"]
    # the weak draft must actually get rejected sometimes — otherwise
    # this test silently stopped exercising the rejection path
    assert 0.0 < snap["acceptance_rate"] < 1.0


def test_spec_sampling_self_draft_accepts_everything(gpt):
    """Leviathan accept on q == p: the ratio is 1, u < 1 always, so
    sampled self-draft acceptance is exactly 1.0 per slot."""
    spec = _engine(gpt, spec_len=2)
    out, _ = _drive(spec, _prompt(31, 6), max_new=8, do_sample=True,
                    top_k=20, seed=4)
    assert out.shape == (14,)
    snap = spec.metrics.snapshot()["speculative"]
    assert snap["acceptance_rate"] == 1.0
    assert all(v == 1.0 for v in snap["per_slot_acceptance"].values())


def test_spec_len_widens_chunk_and_validates():
    paddle.seed(13)
    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=16, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    e = serving.SlotEngine(m, max_slots=1, block_size=8, prefill_chunk=2,
                           spec_len=4)
    assert e.prefill_chunk >= 5          # room for [next, d_1..d_4]
    with pytest.raises(ValueError):
        serving.SlotEngine(m, max_slots=1, block_size=8, spec_len=16)


def test_speculative_accept_matches_target_distribution():
    """Rejection-sampling histogram: accepted-or-resampled tokens from
    (p, q) pairs distribute as p — the Leviathan et al. guarantee the
    engine's sampling path rides on."""
    v = 13
    rng = np.random.RandomState(0)
    p = rng.dirichlet(np.ones(v)).astype(np.float64)
    q = rng.dirichlet(np.ones(v)).astype(np.float64)
    n = 40000
    counts = np.zeros(v)
    for _ in range(n):
        d = int(rng.choice(v, p=q))
        a, resampled = speculative_accept([p], [q], [d], rng)
        counts[d if a == 1 else resampled] += 1
    tv = 0.5 * np.abs(counts / n - p).sum()
    assert tv < 0.02, f"total variation {tv:.4f} vs target"
    # degenerate residual (p == q at the proposal) falls back to p
    a, r = speculative_accept([p], [p], [3],
                              np.random.RandomState(1))
    assert a == 1 and r is None


def test_spec_bulk_scatter_writes_same_pool_rows(gpt):
    """The verify step's bulk KV scatter lands bitwise the same pool
    rows as the plain engine's one-token writes: read both pools back
    through `positions_to_rows` over the identical (ascending) block
    table and compare every committed position."""
    p = _prompt(77, 9)
    max_new = 8

    def pool_rows(eng):
        fut = eng.submit(np.asarray(p, np.int32), max_new_tokens=max_new,
                         timeout=None)
        eng._admit()
        table = None
        while eng.active:
            eng._step()
            for i, s in enumerate(eng._slots):
                if s is not None:
                    table = np.asarray(eng._bt[i]).copy()
        fut.result(10)
        # committed coverage: every prompt/emitted position except the
        # final sampled token (never fed back)
        positions = np.arange(p.size + max_new - 1)
        blk, off = positions_to_rows(table, positions, eng.block_size)
        return [np.asarray(ks)[blk, :, off, :] for ks in eng._ks] + \
               [np.asarray(vs)[blk, :, off, :] for vs in eng._vs]

    rows_plain = pool_rows(_engine(gpt))
    rows_spec = pool_rows(_engine(gpt, spec_len=3))
    for a, b in zip(rows_plain, rows_spec):
        np.testing.assert_allclose(b, a, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# int8 weight path
# ---------------------------------------------------------------------------


def test_dequant_matmul_reference_and_pallas_interpret(monkeypatch):
    """`dequant_matmul` == x @ dequant(q).T against the canonical
    formula on both the lax fallback and the Pallas kernel
    (interpret-mode on CPU via PADDLE_TPU_QUANT_FORCE=pallas)."""
    rng = np.random.RandomState(7)
    x = rng.randn(5, 20).astype(np.float32)
    w = rng.randn(37, 20).astype(np.float32)
    scale = np.float32(np.abs(w).max())
    q = np.clip(np.round(w / scale * 127), -127, 127).astype(np.int8)
    ref = x @ (q.astype(np.float32) * (scale / 127.0)).T

    monkeypatch.setenv("PADDLE_TPU_QUANT_FORCE", "lax")
    lax_out = np.asarray(quant_ops.dequant_matmul(
        jnp.asarray(x), jnp.asarray(q), jnp.asarray(scale)))
    np.testing.assert_allclose(lax_out, ref, rtol=1e-5, atol=1e-5)

    monkeypatch.setenv("PADDLE_TPU_QUANT_FORCE", "pallas")
    t0 = quant_ops._TRACE_COUNT
    pl_out = np.asarray(quant_ops.dequant_matmul(
        jnp.asarray(x), jnp.asarray(q), jnp.asarray(scale)))
    assert quant_ops._TRACE_COUNT > t0        # the kernel really ran
    np.testing.assert_allclose(pl_out, ref, rtol=1e-5, atol=1e-5)
    # leading batch dims reshape through the same kernel
    x3 = rng.randn(2, 3, 20).astype(np.float32)
    out3 = np.asarray(quant_ops.dequant_matmul(
        jnp.asarray(x3), jnp.asarray(q), jnp.asarray(scale)))
    assert out3.shape == (2, 3, 37)


def test_quantize_state_roundtrip_and_manifest(gpt):
    from paddle_tpu.engine import state_values

    vals = state_values(gpt)
    qvals = quantize_state_int8(vals)
    assert is_quantized_state(qvals) and not is_quantized_state(vals)
    frozen = [k for k in qvals if k.endswith(SCALE_SUFFIX)]
    assert frozen                              # 2-D floats froze
    for sk in frozen:
        leaf = sk[: -len(SCALE_SUFFIX)]
        assert np.asarray(qvals[leaf]).dtype == np.int8
        w = np.asarray(vals[leaf], np.float32)
        back = np.asarray(dequantize_state(
            {leaf: qvals[leaf], sk: qvals[sk]})[leaf])
        assert np.abs(back - w).max() <= float(qvals[sk]) / 127.0 + 1e-6


def test_int8_engine_logits_close_to_float(gpt):
    """int8-frozen decode stays within per-tensor-quantization
    tolerance of the bf16/f32 engine's logits, and greedy+speculative
    still run the full request pipeline on the frozen weights."""
    plain = _engine(gpt)
    quant = _engine(gpt, quantize=True)
    assert quant.quantized and not plain.quantized
    assert quant.metrics.snapshot()["speculative"]["dequant_path"] == 1.0
    p = _prompt(12, 6)
    _, f_logits = _drive(plain, p, max_new=4, snoop_first_logits=True)
    _, q_logits = _drive(quant, p, max_new=4, snoop_first_logits=True)
    scale = np.abs(f_logits).max()
    err = np.abs(q_logits - f_logits).max() / max(scale, 1e-9)
    assert err < 0.25, f"int8 logits off by {err:.3f} of full scale"
    # int8 + speculative compose: the spec engine's parity is against
    # its OWN int8 plain twin, bitwise
    qspec = _engine(gpt, quantize=True, spec_len=3)
    for seed in (41, 42):
        pr = _prompt(seed, 7)
        want, _ = _drive(quant, pr, max_new=6)
        got, _ = _drive(qspec, pr, max_new=6)
        np.testing.assert_array_equal(got, want)
    assert qspec.metrics.snapshot()["speculative"]["acceptance_rate"] \
        == 1.0


# ---------------------------------------------------------------------------
# fault sites: serving.draft / serving.verify / serving.dequant
# ---------------------------------------------------------------------------


def test_draft_fault_degrades_to_plain_decode(gpt):
    """A fault in the draft phase (serving.draft) degrades that round
    to plain decode: the slot survives, the output is STILL bitwise
    greedy — no lost or duplicated tokens — and the engine keeps
    speculating on later rounds."""
    plain = _engine(gpt)
    spec = _engine(gpt, spec_len=2)
    p = _prompt(63, 7)
    want, _ = _drive(plain, p, max_new=9)
    with faults.ChaosSchedule("serving.draft@2:raise") as ch:
        got, _ = _drive(spec, p, max_new=9)
        ch.verify()
    np.testing.assert_array_equal(got, want)
    snap = spec.metrics.snapshot()
    assert snap["speculative"]["draft_faults"] == 1
    assert snap["counters"].get("failed", 0) == 0
    # later rounds kept drafting: some proposals were accepted
    assert snap["speculative"]["accepted_tokens"] > 0


def test_verify_fault_fails_step_engine_survives(gpt):
    """serving.verify fires before the verify dispatch; a raise there
    is a step error — in-flight requests fail deterministically, the
    engine stays up and the next request is bitwise clean."""
    spec = _engine(gpt, spec_len=2)
    with faults.ChaosSchedule("serving.verify@2:raise") as ch:
        with pytest.raises(faults.FaultError):
            _drive(spec, _prompt(70, 6), max_new=8)[0]
        ch.verify()
    assert spec.metrics.get("step_errors") == 1
    plain = _engine(gpt)
    p = _prompt(71, 6)
    want, _ = _drive(plain, p, max_new=5)
    got, _ = _drive(spec, p, max_new=5)
    np.testing.assert_array_equal(got, want)


def test_dequant_fault_fires_once_per_quantized_step(gpt):
    """serving.dequant fires each decode step of an int8-frozen engine
    (and never for a float engine); a raise is a plain step error."""
    quant = _engine(gpt, quantize=True)
    with faults.ChaosSchedule("serving.dequant@1:raise") as ch:
        with pytest.raises(faults.FaultError):
            _drive(quant, _prompt(80, 5), max_new=4)[0]
        ch.verify()
    out, _ = _drive(quant, _prompt(81, 5), max_new=4)   # still serves
    assert out.shape == (9,)
    # float engines never pass the site: an exhausted-after-1 schedule
    # on a float drive would fire 0 times
    plain = _engine(gpt)
    with faults.ChaosSchedule("serving.dequant@1-:raise") as ch:
        out, _ = _drive(plain, _prompt(82, 5), max_new=3)
        assert out.shape == (8,)
        assert ch.fired().get("serving.dequant", 0) == 0


# ---------------------------------------------------------------------------
# quantized rollout artifacts
# ---------------------------------------------------------------------------


def test_quantized_weight_version_rolls_out_and_back(gpt):
    """ISSUE 16 satellite: a `WeightVersion.quantized_from` artifact —
    int8 leaves + @scale companions, all in the per-leaf sha256
    manifest, plus the dtype/scale quant summary — rolls out through
    the RolloutController's bitwise golden gate, serves on the dequant
    path, and bitwise-rolls-back, all without breaking compile-once."""
    from paddle_tpu.serving import (
        RolloutController, Router, WeightRegistry, WeightVersion,
    )

    router = Router(gpt, replicas=2,
                    engine_kw=dict(max_slots=2, block_size=8),
                    hedge=False, retry_budget=3, liveness_timeout_s=30.0,
                    backoff_base_s=0.02, name="spec_ro").start()
    try:
        reg = WeightRegistry(gpt)
        ro = RolloutController(router, reg, canary_secs=0.05,
                               wave_size=1, poll_s=0.005,
                               replica_timeout_s=120.0,
                               slo_p99_ms=60000.0)
        wv1 = reg.add(WeightVersion.quantized_from(reg.get(0), 1))
        assert is_quantized_state(wv1.values)
        assert wv1.quant and all(
            rec["dtype"] == "int8" and rec["scale"] > 0.0
            for rec in wv1.quant.values())
        # every int8 leaf AND its @scale companion is manifest-covered
        # (manifest keys use the checkpoint layer's path format)
        for leaf in wv1.quant:
            assert any(leaf in k for k in wv1.manifest)
            assert any(leaf + SCALE_SUFFIX in k for k in wv1.manifest)
        assert "int8" in repr(wv1)

        assert ro.roll_to(1) is True, ro.error
        assert reg.current == 1
        probe = _prompt(90, 6)
        on_v1 = np.asarray(router.generate(probe, max_new_tokens=6,
                                           timeout=60.0))
        for r in router.replica_set.replicas:
            assert r.engine.quantized
            assert r.engine.compile_counts == {"decode": 1, "cow": 1}

        # canary-gate failure on the next target auto-rolls-back to the
        # pinned quantized version, bitwise
        reg.add(WeightVersion.quantized_from(reg.get(1), 2))
        with faults.ChaosSchedule("serving.canary@1:raise") as ch:
            assert ro.roll_to(2) is False
            ch.verify()
        assert ro.state == "rolled_back" and reg.current == 1
        back = np.asarray(router.generate(probe, max_new_tokens=6,
                                          timeout=60.0))
        np.testing.assert_array_equal(back, on_v1)
    finally:
        router.shutdown(drain=True)


# ---------------------------------------------------------------------------
# observability + bench certification
# ---------------------------------------------------------------------------


def test_spec_prometheus_family_and_snapshot(gpt):
    spec = _engine(gpt, spec_len=2, quantize=True)
    _drive(spec, _prompt(55, 6), max_new=8)
    text = observe.prometheus_text(serving=spec.metrics)
    for needle in ("paddle_serving_spec_drafted_tokens_total",
                   "paddle_serving_spec_accepted_tokens_total",
                   "paddle_serving_spec_rejected_tokens_total",
                   "paddle_serving_spec_acceptance_rate",
                   'paddle_serving_spec_slot_acceptance_rate{slot="',
                   "paddle_serving_spec_dequant_path 1"):
        assert needle in text, needle
    # counters are emitted by the generic loop exactly once
    assert sum(
        ln.startswith("paddle_serving_spec_drafted_tokens_total ")
        for ln in text.splitlines()) == 1
    snap = observe.snapshot(serving=spec.metrics)["serving"]
    assert snap["speculative"]["acceptance_rate"] == 1.0
    assert snap["speculative"]["dequant_path"] == 1.0


@pytest.mark.slow
def test_bench_serving_smoke_subprocess():
    """`bench_serving.py --spec --smoke` certifies compile-once, zero
    errors, and the greedy-parity digest in one subprocess. The >=2x
    speedup is asserted by the bench itself on its exit code; under a
    loaded CI box we tolerate a timing miss but never a correctness
    one."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench_serving.py"), "--spec",
         "--smoke"],
        capture_output=True, text=True, timeout=580,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"}, cwd=str(REPO))
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    result = next(json.loads(ln) for ln in lines
                  if json.loads(ln).get("bench") == "BENCH_SERVING_SMOKE")
    assert result["greedy_parity"] is True
    assert result["base"]["errors"] == 0
    assert result["spec"]["errors"] == 0
    assert result["spec"]["digest"] == result["base"]["digest"]
    assert result["base"]["compiles"] == {"decode": 1, "cow": 1}
    assert result["spec"]["compiles"] == {"decode": 1, "draft": 1,
                                          "cow": 1}
    assert result["spec"]["acceptance_rate"] == 1.0
    timing_only = result.get("failures", []) and all(
        "speedup" in f for f in result.get("failures", []))
    assert proc.returncode == 0 or timing_only, \
        (proc.returncode, result.get("failures"), proc.stderr[-800:])
