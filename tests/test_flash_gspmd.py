"""Flash attention inside GSPMD-partitioned programs (VERDICT r4 item 1).

The custom_partitioning rule (fused_ops._flash_fwd_cp/_flash_bwd_cp)
declares batch/heads shardable and runs the same pallas-or-jnp dispatch
per shard, so meshed programs keep the fused kernel instead of falling
back to jnp.  Ref parity: the reference's fused attention kernels run
unmodified under every parallelism because NCCL parallelism is
per-process (paddle/fluid/operators/fused/multihead_matmul_op.cu).
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.ops import fused_ops as fo

pytestmark = pytest.mark.dist

B, H, S, D = 4, 4, 256, 32
SCALE = 1.0 / np.sqrt(D)


def _qkv(seed=0):
    rs = np.random.RandomState(seed)
    return tuple(rs.randn(B, H, S, D).astype(np.float32)
                 for _ in range(3))


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "mp"))


def _meshed_out_and_grads(q, k, v, sharding, dropout_p=0.0):
    seed = jnp.zeros((), jnp.int32)

    def loss(q, k, v):
        o = fo._flash_attention(q, k, v, seed, True, SCALE, dropout_p)
        return jnp.sum(o * o), o

    def step(q, k, v):
        with fo.gspmd_tracing():
            (_, o), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
        return o, grads

    jitted = jax.jit(step, in_shardings=(sharding,) * 3)
    return jitted(*(jax.device_put(t, sharding) for t in (q, k, v)))


def test_meshed_matches_unmeshed():
    """fwd+bwd parity: GSPMD-partitioned (dp x mp over b, h) vs the
    plain single-device path; no fallback warning may fire."""
    q, k, v = _qkv()
    seed = jnp.zeros((), jnp.int32)
    ref_o = fo._flash_attention(q, k, v, seed, True, SCALE, 0.0)
    ref_g = jax.grad(
        lambda *a: jnp.sum(fo._flash_attention(
            *a, seed, True, SCALE, 0.0) ** 2),
        argnums=(0, 1, 2))(q, k, v)

    mesh = _mesh()
    sh = NamedSharding(mesh, P("dp", "mp", None, None))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        o, grads = _meshed_out_and_grads(q, k, v, sh)
    assert o.sharding.spec == P("dp", "mp", None, None)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref_o),
                               rtol=2e-5, atol=2e-5)
    for got, ref in zip(grads, ref_g):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_seq_sharded_operands_get_gathered():
    """Operands arriving seq-sharded must still produce correct output
    (the rule declares seq need_replication; the partitioner inserts
    the gather) — the dedicated seq-parallel path is context_parallel."""
    q, k, v = _qkv(1)
    seed = jnp.zeros((), jnp.int32)
    ref_o = fo._flash_attention(q, k, v, seed, True, SCALE, 0.0)
    mesh = _mesh()
    sh = NamedSharding(mesh, P("dp", None, "mp", None))  # seq on mp!
    o, _ = _meshed_out_and_grads(q, k, v, sh)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref_o),
                               rtol=2e-5, atol=2e-5)


def test_pallas_path_taken_inside_partitioned_program(monkeypatch):
    """With PADDLE_TPU_FLASH_FORCE=pallas the per-shard lowering must
    invoke the ACTUAL pallas kernels (interpret mode on the CPU mesh),
    not the jnp fallback — certifies the Mosaic call survives GSPMD."""
    monkeypatch.setenv("PADDLE_TPU_FLASH_FORCE", "pallas")
    calls = {"fwd": 0, "bwd": 0}
    real_fwd, real_bwd = fo._flash_fwd_pallas, fo._flash_bwd_pallas

    def spy_fwd(*a, **kw):
        calls["fwd"] += 1
        return real_fwd(*a, **kw)

    def spy_bwd(*a, **kw):
        calls["bwd"] += 1
        return real_bwd(*a, **kw)

    monkeypatch.setattr(fo, "_flash_fwd_pallas", spy_fwd)
    monkeypatch.setattr(fo, "_flash_bwd_pallas", spy_bwd)

    q, k, v = _qkv(2)
    seed = jnp.zeros((), jnp.int32)
    ref_o = np.asarray(fo._fwd_impl4(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), seed,
        True, SCALE, 0.0)[0])
    assert calls["fwd"] == 1  # sanity: the spy sees the plain path

    mesh = _mesh()
    sh = NamedSharding(mesh, P("dp", "mp", None, None))
    o, grads = _meshed_out_and_grads(q, k, v, sh)
    assert calls["fwd"] >= 2, "pallas fwd not traced inside partition"
    assert calls["bwd"] >= 1, "pallas bwd not traced inside partition"
    np.testing.assert_allclose(np.asarray(o), ref_o, rtol=2e-5,
                               atol=2e-5)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()


def test_dropout_runs_meshed_and_scales():
    """Dropout inside the partitioned program: output stays unbiased
    (mean magnitude comparable to no-dropout) and finite; per-shard
    streams are decorrelated by the shard-id seed fold."""
    q, k, v = _qkv(3)
    mesh = _mesh()
    sh = NamedSharding(mesh, P("dp", "mp", None, None))
    o_p, _ = _meshed_out_and_grads(q, k, v, sh, dropout_p=0.3)
    o_0, _ = _meshed_out_and_grads(q, k, v, sh, dropout_p=0.0)
    a, b = np.asarray(o_p), np.asarray(o_0)
    assert np.isfinite(a).all()
    assert not np.allclose(a, b)          # dropout actually applied
    # unbiased rescale keeps magnitudes in the same ballpark
    ratio = np.abs(a).mean() / np.abs(b).mean()
    assert 0.7 < ratio < 1.4, ratio


def test_engine_meshed_uses_cp_path():
    """An Engine built with a mesh must trace attention through the
    custom_partitioning wrappers (the gspmd_tracing gate) and still
    reproduce the unmeshed loss."""
    import paddle_tpu as paddle
    from paddle_tpu.engine import Engine
    from paddle_tpu import nn

    class TinyAttn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(D, D)

        def forward(self, x):
            # x: [b, h, s, d] pre-split heads (bhsd layout)
            o = paddle.nn.functional.scaled_dot_product_attention(
                x, x, x, is_causal=True, qkv_layout="bhsd")
            return self.proj(o).mean()

    def build(mesh):
        paddle.seed(7)
        model = TinyAttn()
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=model.parameters())
        kwargs = {}
        if mesh is not None:
            kwargs = dict(mesh=mesh,
                          batch_spec=NamedSharding(mesh, P("dp")))
        return Engine(model, opt, lambda out, y: out, **kwargs)

    x = np.random.RandomState(4).randn(B, H, S, D).astype(np.float32)
    y = np.zeros((B,), np.float32)
    ref = float(build(None).train_batch((x,), (y,)).item())
    mesh = _mesh()
    got = float(build(mesh).train_batch((x,), (y,)).item())
    np.testing.assert_allclose(got, ref, rtol=1e-4)
