"""Training payload for the fork-based gang chaos tests
(test_gang_slow.py): a tiny deterministic SGD loop whose gradients are
averaged cross-rank over the p2p mailbox (so a SIGKILLed peer leaves
the survivor blocked inside a real collective), checkpointed through
the GANG commit barrier, killable/hangable at a scripted step.

Env contract (set by the test, plus the launcher's PADDLE_* vars):
  GANG_OUT         output dir (losses / typed-error / checkpoint files)
  GANG_STEPS       total steps to complete
  GANG_KILL_RANK / GANG_KILL_STEP   SIGKILL self mid-collective there
                                    (first attempt only)
  GANG_HANG_RANK / GANG_HANG_STEP   go silent there (first attempt only)
"""

import os
import signal
import sys
import time

import numpy as np

# run as `python tests/gang_payload.py`: the script dir (tests/) is on
# sys.path, the repo root is not
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.distributed import preempt  # noqa: E402
from paddle_tpu.distributed.checkpoint import (  # noqa: E402
    GangCheckpointManager)
from paddle_tpu.distributed.gang import (  # noqa: E402
    CollectiveTimeoutError, GangWorker, PeerGoneError, allreduce_host)


def main():
    out = os.environ["GANG_OUT"]
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    attempt = int(os.environ.get("PADDLE_GANG_ATTEMPT", "1"))
    steps = int(os.environ.get("GANG_STEPS", "8"))
    kill_rank = int(os.environ.get("GANG_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("GANG_KILL_STEP", "-1"))
    hang_rank = int(os.environ.get("GANG_HANG_RANK", "-1"))
    hang_step = int(os.environ.get("GANG_HANG_STEP", "-1"))

    preempt.install()
    gw = GangWorker()
    mgr = GangCheckpointManager(os.path.join(out, "ckpt"), rank, world)
    w = np.linspace(-0.5, 0.5, 4)
    start = 0
    if mgr.latest_committed_step() is not None:
        got, st = mgr.restore({"w": w})
        w, start = np.asarray(st["w"]), got + 1
    lossf = open(os.path.join(out, f"losses.r{rank}.log"), "a")
    try:
        for step in range(start, steps):
            gw.beat(step=step)
            if rank == hang_rank and step == hang_step and attempt == 1:
                while True:
                    time.sleep(0.5)
            if rank == kill_rank and step == kill_step and attempt == 1:
                time.sleep(0.3)  # ensure the peer is already blocked
                os.kill(os.getpid(), signal.SIGKILL)
            rng = np.random.RandomState(31 * step + rank)
            x, y = rng.randn(8, 4), rng.randn(8)
            err = x @ w - y
            g = allreduce_host((2.0 / len(y)) * (x.T @ err), "mean",
                               rank=rank, world=world)
            w = w - 0.05 * g
            if rank == 0:
                loss = float(np.mean(err * err))
                lossf.write(f"{step} {loss.hex()}\n")
                lossf.flush()
            if (step + 1) % 2 == 0:
                mgr.save(step, {"w": w})
    except (CollectiveTimeoutError, PeerGoneError) as e:
        with open(os.path.join(out, f"typed.r{rank}.log"), "a") as f:
            f.write(f"{type(e).__name__}: {e}\n")
        sys.exit(13)
    return 0


if __name__ == "__main__":
    sys.exit(main())
