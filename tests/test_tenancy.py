"""Multi-tenant serving platform (ISSUE 20): batched LoRA adapter
banks inside the one compiled decode step, the (model, adapter,
version) artifact catalog, weighted-fair (deficit round robin)
per-tenant admission with token budgets, tier-based brownout, and
per-tenant metrics.

The invariants certified here:

- N adapters serve batched in ONE decode step: a mixed-adapter wave
  (with slot recycling) produces, per slot, tokens bitwise-equal to a
  single-adapter engine running that adapter alone; adapter row 0 is
  the base model and stays bitwise-identical to an adapter-less engine.
- Adapter banks hot-swap through the rollout-commit path with ZERO
  retraces: compile_counts stays {"decode": 1, "cow": 1} for engine
  life, and a mid-swap fault (site ``serving.adapter_swap``) aborts
  all-or-nothing — the OLD bank keeps serving bitwise.
- `TenantFairQueue` runs DRR weighted fair queueing: a flooding tenant
  only drains its own share; token budgets shed with a typed 429
  (`TenantBudgetError`) carrying the bucket's exact refill wait, and
  fault site ``serving.admit_tenant`` injects the same shed
  deterministically.
- The fleet Router sheds by tenant TIER during brownout when a
  `TenantDirectory` is attached, and `AdapterRollout` drives
  canary -> wave -> commit with all-or-nothing fleet-wide rollback.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.framework import faults
from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining
from paddle_tpu.serving import (
    AdapterRollout, ArtifactCatalog, BrownoutShedError, Request, Router,
    TenantBudgetError, TenantDirectory, TenantFairQueue, TenantSpec,
)
from paddle_tpu.serving.engine import SlotEngine
from paddle_tpu.serving.tenancy import DEFAULT_TENANT, SLO_TIERS

VOCAB = 31
HIDDEN = 32
RANK = 4
N_ADAPTERS = 3


@pytest.fixture(scope="module")
def gpt():
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _bank(seed=7, scale=0.5):
    """A deterministic stacked adapter bank; row 0 all-zero (base)."""
    rng = np.random.RandomState(seed)
    la = np.zeros((N_ADAPTERS, RANK, HIDDEN), np.float32)
    lb = np.zeros((N_ADAPTERS, VOCAB, RANK), np.float32)
    la[1:] = rng.randn(N_ADAPTERS - 1, RANK, HIDDEN).astype(
        np.float32) * scale
    lb[1:] = rng.randn(N_ADAPTERS - 1, VOCAB, RANK).astype(
        np.float32) * scale
    return la, lb


def _prompt(seed, n=6):
    return np.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(np.int32)


@pytest.fixture(scope="module")
def adapter_engine(gpt):
    """Shared adapter-bank engine: the parity/zero-retrace/fault tests
    reuse it so the compile-once invariant is checked ACROSS swaps and
    mixed waves."""
    eng = SlotEngine(gpt, max_slots=2, block_size=8,
                     max_adapters=N_ADAPTERS, lora_rank=RANK)
    eng.warmup()
    eng.start()
    la, lb = _bank()
    eng.swap_adapters(la, lb)
    yield eng
    eng.shutdown(drain=False)


@pytest.fixture(scope="module")
def ref_engine(gpt):
    """The single-adapter reference: same bank, but every wave it
    serves uses one adapter alone."""
    eng = SlotEngine(gpt, max_slots=2, block_size=8,
                     max_adapters=N_ADAPTERS, lora_rank=RANK)
    eng.warmup()
    eng.start()
    la, lb = _bank()
    eng.swap_adapters(la, lb)
    yield eng
    eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# tenant spec / directory
# ---------------------------------------------------------------------------


def test_tenant_spec_validation():
    with pytest.raises(ValueError):
        TenantSpec("x", slo_class="platinum")
    with pytest.raises(ValueError):
        TenantSpec("x", weight=0)
    s = TenantSpec("x", slo_class="gold")
    assert s.tier == SLO_TIERS["gold"] == 2
    assert s.unlimited and s.budget_remaining() is None


def test_token_bucket_debit_and_refill():
    s = TenantSpec("t", budget_tokens_per_s=100, burst_s=0.5)
    ok, wait = s.try_debit(40)
    assert ok and wait == 0.0
    ok, wait = s.try_debit(40)         # 10 left of the 50 burst
    assert not ok
    # refill must cover exactly the 30-token shortfall at 100 tok/s
    assert wait == pytest.approx(0.3, abs=0.05)
    assert s.budget_remaining() <= 50


def test_directory_resolve_and_brownout_floor():
    d = TenantDirectory([TenantSpec("gold-co", slo_class="gold")],
                        brownout_tier=1)
    assert d.resolve("gold-co").tier == 2
    assert d.resolve(None).name == DEFAULT_TENANT
    # unknown tenants auto-create a bronze default — admission never
    # fails on an unregistered name
    assert d.resolve("walk-in").tier == 0
    assert "walk-in" in d
    assert d.brownout_tier == 1
    snap = d.snapshot()
    assert snap["gold-co"]["slo_class"] == "gold"


def test_directory_mapping_form():
    d = TenantDirectory({"a": {"weight": 2.0},
                         "b": TenantSpec("b", priority=1)})
    assert d.resolve("a").weight == 2.0
    assert d.resolve("b").priority == 1


# ---------------------------------------------------------------------------
# weighted-fair admission
# ---------------------------------------------------------------------------


def _req(tenant, max_new=4, n=4):
    return Request(np.arange(1, n + 1, dtype=np.int32),
                   max_new_tokens=max_new, tenant=tenant)


def test_wfq_weighted_share_no_starvation():
    """A flooding weight-1 tenant cannot starve a weight-4 tenant: DRR
    serves the vip's whole backlog within the first rotation."""
    d = TenantDirectory([TenantSpec("flood", weight=1.0),
                         TenantSpec("vip", weight=4.0)])
    q = TenantFairQueue(64, tenancy=d, quantum=8)
    for _ in range(20):
        q.submit(_req("flood"))
    for _ in range(4):
        q.submit(_req("vip"))
    order = []
    while q.depth:
        r = q.pop(timeout=0.5)
        assert r is not None
        order.append(r.gen["tenant"])
    assert len(order) == 24
    # every vip head lands in the first 8 pops despite arriving last
    assert max(i for i, t in enumerate(order) if t == "vip") < 8
    depths = q.tenant_depths()
    assert depths == {}


def test_wfq_requeue_preserves_head_of_line(gpt):
    d = TenantDirectory()
    q = TenantFairQueue(8, tenancy=d, quantum=8)
    a, b = _req("t1"), _req("t1")
    q.submit(a)
    q.submit(b)
    got = q.pop(timeout=0.5)
    assert got is a
    q.requeue(got)
    assert q.pop(timeout=0.5) is a      # requeued head served first
    assert q.pop(timeout=0.5) is b


def test_budget_shed_carries_refill_wait():
    d = TenantDirectory([TenantSpec("tiny", budget_tokens_per_s=10,
                                    burst_s=1.0)])
    metrics = serving.ServingMetrics()
    q = TenantFairQueue(64, tenancy=d, metrics=metrics)
    q.submit(_req("tiny", max_new=2))     # cost 6 of the 10 burst
    with pytest.raises(TenantBudgetError) as ei:
        q.submit(_req("tiny", max_new=4))  # cost 8 > 4 left
    assert ei.value.status == 429
    assert ei.value.retriable
    assert 0 < ei.value.retry_after_s <= 1.0
    assert metrics.get("rejected_budget") == 1


def test_admit_tenant_fault_drop_sheds_one_tenant():
    """A ``drop`` at serving.admit_tenant is a deterministic per-tenant
    shed: the tagged tenant 429s, other tenants keep flowing."""
    d = TenantDirectory()
    metrics = serving.ServingMetrics()
    q = TenantFairQueue(64, tenancy=d, metrics=metrics)
    with faults.ChaosSchedule(
            "serving.admit_tenant[noisy]@1:drop") as ch:
        with pytest.raises(TenantBudgetError):
            q.submit(_req("noisy"))
        ok = q.submit(_req("quiet"))
        ch.verify()
    assert q.pop(timeout=0.5) is ok
    snap = metrics.snapshot()
    assert snap["tenants"]["noisy"]["counters"]["shed"] == 1


# ---------------------------------------------------------------------------
# batched adapters in the unified decode step
# ---------------------------------------------------------------------------


def test_adapter_zero_row_matches_base_engine(gpt, adapter_engine):
    """Adapter row 0 is the base model: with a live non-zero bank in
    rows 1.., adapter_id=0 must stay bitwise-identical to an engine
    built without adapters at all."""
    plain = SlotEngine(gpt, max_slots=2, block_size=8)
    plain.warmup()
    plain.start()
    try:
        p = _prompt(0)
        ref = plain.submit(p, max_new_tokens=8).result(60)
        out = adapter_engine.submit(p, max_new_tokens=8,
                                    adapter_id=0).result(60)
        np.testing.assert_array_equal(out, ref)
    finally:
        plain.shutdown(drain=False)


def test_mixed_adapter_wave_bitwise_vs_single_adapter(adapter_engine,
                                                      ref_engine):
    """The acceptance invariant: N adapters batched in one decode step,
    each slot's tokens bitwise-equal to a single-adapter engine running
    that adapter alone — across mixed waves AND slot recycling (3x more
    requests than slots)."""
    prompts = [_prompt(s) for s in range(6)]
    refs = {}
    for aid in range(N_ADAPTERS):
        # the reference serves each adapter ALONE (sequential waves)
        futs = [ref_engine.submit(p, max_new_tokens=8, adapter_id=aid)
                for p in prompts]
        refs[aid] = [f.result(60) for f in futs]
    # mixed wave: interleave all adapters at once over 2 slots
    futs = [(i, aid, adapter_engine.submit(
        prompts[i], max_new_tokens=8, adapter_id=aid,
        tenant=f"tenant-{aid}"))
        for i in range(6) for aid in range(N_ADAPTERS)]
    for i, aid, f in futs:
        np.testing.assert_array_equal(
            f.result(60), refs[aid][i],
            err_msg=f"prompt {i} adapter {aid} diverged in mixed wave")
    # different adapters on the same prompt actually decode differently
    assert not np.array_equal(refs[0][0], refs[1][0])
    assert not np.array_equal(refs[1][0], refs[2][0])


def test_adapter_swap_zero_retrace(adapter_engine):
    """Hot-swapping banks and serving every adapter must never retrace:
    compile_counts stays {decode: 1, cow: 1} for engine life."""
    la, lb = _bank(seed=23, scale=0.3)
    v0 = adapter_engine.adapter_version
    v1 = adapter_engine.swap_adapters(la, lb)
    assert v1 == v0 + 1
    futs = [adapter_engine.submit(_prompt(9), max_new_tokens=4,
                                  adapter_id=aid)
            for aid in range(N_ADAPTERS)]
    for f in futs:
        f.result(60)
    assert adapter_engine.compile_counts == {"decode": 1, "cow": 1}
    # restore the canonical bank for the other module tests
    adapter_engine.swap_adapters(*_bank())


def test_adapter_swap_validation(adapter_engine):
    la, lb = _bank()
    with pytest.raises(ValueError):       # wrong rank: rebuild, not swap
        adapter_engine.swap_adapters(la[:, :2], lb[:, :, :2])
    bad_a = la.copy()
    bad_a[0, 0, 0] = 1.0                  # row 0 must stay base
    with pytest.raises(ValueError):
        adapter_engine.swap_adapters(bad_a, lb)
    with pytest.raises(ValueError):       # id outside the bank
        adapter_engine.submit(_prompt(1), max_new_tokens=2,
                              adapter_id=N_ADAPTERS)


def test_mid_swap_fault_leaves_old_bank_serving_bitwise(adapter_engine):
    """serving.adapter_swap fires BEFORE any mutation: a faulted swap
    is all-or-nothing and the old bank keeps serving bitwise."""
    p = _prompt(3)
    before = [adapter_engine.submit(p, max_new_tokens=8,
                                    adapter_id=aid).result(60)
              for aid in range(N_ADAPTERS)]
    ver = adapter_engine.adapter_version
    la, lb = _bank(seed=99, scale=1.0)
    with faults.ChaosSchedule("serving.adapter_swap@1:raise") as ch:
        with pytest.raises(faults.FaultError):
            adapter_engine.swap_adapters(la, lb)
        ch.verify()
    assert adapter_engine.adapter_version == ver
    after = [adapter_engine.submit(p, max_new_tokens=8,
                                   adapter_id=aid).result(60)
             for aid in range(N_ADAPTERS)]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(a, b)
    assert adapter_engine.compile_counts == {"decode": 1, "cow": 1}


def test_engine_without_adapters_rejects_swap_and_ids(gpt):
    eng = SlotEngine(gpt, max_slots=1, block_size=8)
    with pytest.raises(ValueError):
        eng.swap_adapters(*_bank())
    with pytest.raises(ValueError):
        eng.submit(_prompt(1), max_new_tokens=2, adapter_id=1)


# ---------------------------------------------------------------------------
# artifact catalog
# ---------------------------------------------------------------------------


def test_artifact_catalog_lines_and_digests():
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.serving.rollout import artifact_digest

    cat = ArtifactCatalog()
    w = {"w": np.arange(8, dtype=np.float32)}
    a1 = cat.add("model", "base", values=w)
    assert a1.version == 1 and a1.state == "registered"
    assert a1.digest == artifact_digest(ckpt.leaf_digests(
        {k: np.asarray(v) for k, v in w.items()}))
    la, lb = _bank()
    b1 = cat.add("adapter", "support-bot",
                 values={"lora_a": la, "lora_b": lb})
    b2 = cat.add("adapter", "support-bot",
                 values={"lora_a": la * 2, "lora_b": lb})
    assert (b1.version, b2.version) == (1, 2)
    assert b1.digest != b2.digest
    # lines roll independently: committing the adapter line never
    # touches the model line
    cat.commit("adapter", "support-bot", 2)
    assert cat.serving_version("adapter", "support-bot") == 2
    assert cat.serving_version("model", "base") is None
    assert cat.get("adapter", "support-bot").version == 2
    cat.commit("adapter", "support-bot", 1)    # roll back: 2 demoted
    assert b2.state == "registered" and b1.state == "serving"
    cat.retire("adapter", "support-bot", 1)
    with pytest.raises(KeyError):
        cat.get("adapter", "support-bot", 1)
    assert cat.get("adapter", "support-bot").version == 2  # latest live
    with pytest.raises(ValueError):
        cat.add("adapter", "support-bot", values={"x": la}, version=1)
    assert cat.lines() == [("adapter", "support-bot"),
                           ("model", "base")]


# ---------------------------------------------------------------------------
# fleet: tier brownout, adapter rollout, per-tenant export
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tenant_router(gpt):
    ten = TenantDirectory(
        [TenantSpec("gold-co", weight=4.0, slo_class="gold",
                    priority=2),
         TenantSpec("best-effort", weight=1.0, slo_class="bronze")],
        brownout_tier=1)
    router = Router(
        gpt, 2,
        engine_kw=dict(max_slots=2, block_size=8,
                       max_adapters=N_ADAPTERS, lora_rank=RANK),
        tenancy=ten, hedge=False, name="tenfleet")
    router.start()
    yield router
    router.shutdown(drain=False)


def test_router_sheds_by_tenant_tier_in_brownout(tenant_router):
    router = tenant_router
    router.set_brownout(True)
    try:
        with pytest.raises(BrownoutShedError):
            router.submit(_prompt(1), max_new_tokens=2,
                          tenant="best-effort")
        # gold rides through the same brownout
        out = router.submit(_prompt(1), max_new_tokens=2,
                            tenant="gold-co").result(60)
        assert out is not None
    finally:
        router.set_brownout(None)
    snap = router.metrics.snapshot()
    assert snap["tenants"]["best-effort"]["counters"]["shed"] >= 1
    assert snap["tenants"]["gold-co"]["counters"].get("shed", 0) == 0


def test_adapter_rollout_canary_wave_commit(tenant_router):
    ro = AdapterRollout(tenant_router, name="support-bot")
    la, lb = _bank(seed=31, scale=0.4)
    art = ro.roll_to(la, lb, probe=_prompt(2))
    assert ro.state == "committed" and ro.error is None
    assert ro.catalog.serving_version("adapter", "support-bot") \
        == art.version
    engines = [r.engine for r in tenant_router.replica_set.healthy()]
    assert all(e.adapter_version == art.version for e in engines)
    for e in engines:
        np.testing.assert_array_equal(np.asarray(e._lora_a), la)


def test_adapter_rollout_faulted_wave_rolls_back(tenant_router):
    """A fault on the SECOND replica's swap mid-wave restores the old
    bank on the already-swapped canary and retires the new version —
    all-or-nothing fleet-wide, bitwise."""
    engines = [r.engine for r in tenant_router.replica_set.healthy()]
    assert len(engines) == 2
    p = _prompt(4)
    before = [e.submit(p, max_new_tokens=8, adapter_id=1).result(60)
              for e in engines]
    vers = [e.adapter_version for e in engines]
    ro = AdapterRollout(tenant_router, name="support-bot")
    la, lb = _bank(seed=77, scale=0.9)
    with faults.ChaosSchedule("serving.adapter_swap@2:raise") as ch:
        with pytest.raises(faults.FaultError):
            ro.roll_to(la, lb)
        ch.verify()
    assert ro.state == "rolled_back"
    assert "FaultError" in ro.error
    new_ver = max(
        ro.catalog._lines[("adapter", "support-bot")])
    assert ro.catalog.serving_version("adapter",
                                      "support-bot") != new_ver
    after = [e.submit(p, max_new_tokens=8, adapter_id=1).result(60)
             for e in engines]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(a, b)
    assert [e.adapter_version for e in engines] == vers


def test_tenant_prometheus_families(tenant_router):
    from paddle_tpu import observe

    text = observe.prometheus_text(serving=tenant_router.metrics)
    assert 'paddle_tenant_completed_total{tenant="gold-co"}' in text
    assert 'paddle_tenant_qps{tenant="gold-co"}' in text
    assert 'paddle_tenant_shed_total{tenant="best-effort"}' in text
    assert 'paddle_tenant_latency_seconds{tenant="gold-co"' in text


# ---------------------------------------------------------------------------
# workload tenant mix + HTTP front
# ---------------------------------------------------------------------------


def test_workload_tenant_mix_deterministic_roundtrip():
    sc = serving.Scenario(
        name="mix", seed=5, vocab=VOCAB, n_users=8,
        phases=[{"duration_s": 3.0, "rate_rps": 10.0}],
        tenants={"gold-co": {"weight": 1.0, "priority": 2},
                 "best-effort": {"weight": 3.0}})
    t1 = sc.trace()
    assert t1, "empty trace"
    assert all(a.tenant in ("gold-co", "best-effort") for a in t1)
    # the tenant dict's priority overrides the drawn class
    assert all(a.priority == 2 for a in t1 if a.tenant == "gold-co")
    seen = {a.tenant for a in t1}
    assert seen == {"gold-co", "best-effort"}
    # JSON-roundtrip determinism: same spec, bitwise-same trace
    t2 = serving.Scenario.from_json(sc.to_json()).trace()
    assert len(t1) == len(t2)
    for a, b in zip(t1, t2):
        assert (a.t, a.tenant, a.priority, a.max_new) == \
            (b.t, b.tenant, b.priority, b.max_new)
        np.testing.assert_array_equal(a.prompt, b.prompt)


def test_workload_without_tenants_unchanged():
    """tenants=None consumes no extra RNG: the legacy trace shape is
    bit-identical and `to_dict` carries no tenants key."""
    sc = serving.Scenario(seed=3, n_users=4,
                          phases=[{"duration_s": 2.0, "rate_rps": 8.0}])
    assert "tenants" not in sc.to_dict()
    for a in sc.trace():
        assert a.tenant is None


def test_http_front_x_tenant_and_budget_429(gpt):
    ten = TenantDirectory(
        [TenantSpec("metered", budget_tokens_per_s=12, burst_s=1.0)])
    srv = serving.Server(gpt, max_slots=2, block_size=8,
                         max_adapters=2, lora_rank=RANK,
                         tenancy=ten).start()
    httpd = serving.http_front(srv)
    port = httpd.server_address[1]
    try:
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate", data=body,
            headers={"X-Tenant": "metered"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            assert len(json.loads(r.read())["ids"]) == 7
        # the tenant's bucket (12 tokens) is now empty enough that the
        # next metered call sheds with ITS refill time as Retry-After
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"prompt": [1, 2, 3],
                                 "max_new_tokens": 8,
                                 "tenant": "metered"}).encode()))
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) > 0
        payload = json.loads(ei.value.read())
        assert payload["type"] == "TenantBudgetError"
        assert payload["retriable"]
        # anonymous traffic is untouched by the metered tenant's budget
        with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=body)) as r:
            assert r.status == 200
        snap = srv.snapshot()
        assert snap["tenants"]["metered"]["counters"]["completed"] == 1
        assert snap["tenants"]["metered"]["counters"]["shed"] == 1
    finally:
        httpd.shutdown()
        srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# bench subprocess smoke (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_fleet_tenants_smoke():
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TPU_FAULTS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench_fleet.py"),
         "--tenants", "--smoke"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=570)
    assert r.returncode == 0, r.stderr[-4000:]
    assert "SMOKE OK" in r.stdout
    final = json.loads(r.stdout.strip().splitlines()[-2])
    assert final["bench"] == "BENCH_FLEET_TENANTS"
    assert final["chaos"]["tenants"]["crowd"]["shed"] == 3
    assert final["chaos"]["tenants"]["steady"]["shed"] == 0
