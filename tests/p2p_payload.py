"""Payload for the eager p2p test: 2 ranks exchange tensors through
paddle.distributed.send/recv (ref send_v2/recv_v2 unit flows) — a
ping-pong with ordering and a self-send."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.core.tensor import Tensor  # noqa: E402
from paddle_tpu.distributed import collective  # noqa: E402

rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))

if rank == 0:
    # two ordered sends, then await the doubled reply
    collective.send(Tensor(np.full((4,), 1.0, np.float32)), dst=1)
    collective.send(Tensor(np.full((4,), 2.0, np.float32)), dst=1)
    out = Tensor(np.zeros((4,), np.float32))
    collective.recv(out, src=1)
    np.testing.assert_allclose(np.asarray(out.numpy()), 6.0)
    # self-send round-trips through the local queue
    collective.send(Tensor(np.arange(3, dtype=np.float32)), dst=0)
    self_out = Tensor(np.zeros((3,), np.float32))
    collective.recv(self_out, src=0)
    np.testing.assert_allclose(np.asarray(self_out.numpy()), [0, 1, 2])
else:
    a = Tensor(np.zeros((4,), np.float32))
    b = Tensor(np.zeros((4,), np.float32))
    collective.recv(a, src=0)
    collective.recv(b, src=0)
    # TCP ordering: first send arrives first
    np.testing.assert_allclose(np.asarray(a.numpy()), 1.0)
    np.testing.assert_allclose(np.asarray(b.numpy()), 2.0)
    collective.send((a + b) * 2, dst=0)

print(f"RANK {rank} P2P OK", flush=True)
