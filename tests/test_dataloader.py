"""DataLoader tests: multiprocess workers, ordering, error propagation,
device prefetch, native datafeed fast path.

Ref parity: python/paddle/fluid/tests/unittests/test_dataloader_*.py +
test_multiprocess_dataloader_*.py.
"""

from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import native
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import (
    DataLoader, Dataset, IterableDataset, TensorDataset,
    DistributedBatchSampler, get_worker_info,
)


class SquareDataset(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((3,), i, np.float32),
                np.asarray(i * i, np.int64))


class FailingDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at index 5")
        return np.zeros((2,), np.float32)


def _collect(loader):
    xs, ys = [], []
    for x, y in loader:
        xs.append(np.asarray(x.numpy()))
        ys.append(np.asarray(y.numpy()))
    return np.concatenate(xs), np.concatenate(ys)


def test_single_process_vs_multiprocess_same_batches():
    ds = SquareDataset(37)
    a = _collect(DataLoader(ds, batch_size=5, num_workers=0,
                            use_buffer_reader=False))
    b = _collect(DataLoader(ds, batch_size=5, num_workers=3,
                            use_buffer_reader=False))
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    # order preserved: sequential sampler -> x rows are 0..36 in order
    np.testing.assert_array_equal(a[0][:, 0], np.arange(37))


def test_multiprocess_worker_error_propagates():
    loader = DataLoader(FailingDataset(), batch_size=4, num_workers=2,
                        use_buffer_reader=False)
    with pytest.raises(RuntimeError, match="ValueError"):
        list(loader)


def test_multiprocess_shuffle_epoch():
    ds = SquareDataset(64)
    loader = DataLoader(ds, batch_size=8, shuffle=True, num_workers=2,
                        use_buffer_reader=False)
    x1, _ = _collect(loader)
    assert sorted(x1[:, 0].tolist()) == list(range(64))


def test_device_prefetch_yields_device_arrays():
    ds = SquareDataset(12)
    loader = DataLoader(ds, batch_size=4, use_buffer_reader=True)
    batches = list(loader)
    assert len(batches) == 3
    x, y = batches[0]
    assert isinstance(x, Tensor)
    import jax

    assert isinstance(x._value, jax.Array)
    np.testing.assert_array_equal(x.numpy()[:, 0], np.arange(4))


def test_native_fast_path_matches_python_path():
    assert native.available(), "native datafeed must build in this image"
    xs = np.random.RandomState(0).rand(50, 7).astype(np.float32)
    ys = np.arange(50, dtype=np.int64)
    ds = TensorDataset([xs, ys])
    fast = DataLoader(ds, batch_size=8, use_buffer_reader=False)
    assert fast._can_use_native()
    out_x, out_y = _collect(
        DataLoader(ds, batch_size=8, use_buffer_reader=False))
    np.testing.assert_allclose(out_x, xs, rtol=0, atol=0)
    np.testing.assert_array_equal(out_y, ys)


def test_native_gather_matches_numpy():
    if not native.available():
        pytest.skip("no toolchain")
    for dtype in (np.float32, np.uint8, np.int32, np.int64):
        src = (np.random.RandomState(1).rand(100, 6) * 50).astype(dtype)
        idx = np.random.RandomState(2).randint(0, 100, 33)
        np.testing.assert_array_equal(native.gather_rows(src, idx),
                                      src[idx])
    img = (np.random.RandomState(3).rand(40, 8, 9, 3) * 255).astype(
        np.uint8)
    idx = np.random.RandomState(4).randint(0, 40, 16)
    got = native.gather_images_u8_chw(img, idx, scale=1 / 255.0,
                                      shift=-0.5)
    ref = np.transpose(img[idx].astype(np.float32) / 255.0 - 0.5,
                       (0, 3, 1, 2))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_worker_init_fn_and_worker_info():
    seen = []

    class ProbeDataset(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = get_worker_info()
            assert info is not None and 0 <= info.id < 2
            return np.asarray([i, info.id], np.int64)

    loader = DataLoader(ProbeDataset(), batch_size=2, num_workers=2,
                        use_buffer_reader=False)
    rows = np.concatenate([b.numpy() for b in
                           (x[0] if isinstance(x, list) else x
                            for x in loader)])
    assert get_worker_info() is None  # main process


def test_distributed_batch_sampler_partitions():
    ds = SquareDataset(20)
    seen = []
    for rank in range(2):
        sampler = DistributedBatchSampler(ds, batch_size=5,
                                          num_replicas=2, rank=rank)
        for batch in sampler:
            seen.extend(batch)
    assert sorted(seen) == list(range(20))


def test_iterable_dataset_with_workers_uses_thread_path():
    class Stream(IterableDataset):
        def __iter__(self):
            return iter(np.arange(10, dtype=np.float32))

    loader = DataLoader(Stream(), batch_size=4, num_workers=2,
                        use_buffer_reader=False)
    batches = [b.numpy() for b in loader]
    np.testing.assert_array_equal(np.concatenate(batches),
                                  np.arange(10, dtype=np.float32))
