"""Dataset ingestion + Trainer/DeviceWorker loop.

Ref intent: unittests/test_dataset.py (InMemoryDataset/QueueDataset
set_filelist/load_into_memory/shuffle + run_from_dataset) and
test_trainer_desc.py — file-list slot parsing, sharded loading,
hogwild threads, and Executor.train_from_dataset over a static Program.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.framework import (
    DatasetFactory, InMemoryDataset, MultiSlotDataFeed, MultiTrainer,
    QueueDataset,
)


def _write_files(tmp_path, n_files=2, lines_per_file=8, dim=4, seed=0):
    """MultiSlot text format: ids slot (2 ids) + dense float slot (dim) +
    label slot (1 float)."""
    rng = np.random.RandomState(seed)
    paths = []
    for f in range(n_files):
        p = tmp_path / f"part-{f}.txt"
        rows = []
        for _ in range(lines_per_file):
            ids = rng.randint(0, 50, 2)
            x = rng.randn(dim)
            y = [float(x.sum() > 0)]
            rows.append(
                f"2 {ids[0]} {ids[1]} "
                f"{dim} " + " ".join(f"{v:.6f}" for v in x)
                + f" 1 {y[0]}")
        p.write_text("\n".join(rows) + "\n")
        paths.append(str(p))
    return paths


_SLOTS = [("ids", "int64", 2), ("x", "float", 4), ("label", "float", 1)]


def test_multislot_parse_and_batch(tmp_path):
    paths = _write_files(tmp_path)
    ds = InMemoryDataset()
    ds.set_filelist(paths)
    ds.set_batch_size(4)
    ds.set_feed(MultiSlotDataFeed(_SLOTS))
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 16
    batches = list(ds)
    assert len(batches) == 4
    b = batches[0]
    assert b["ids"].shape == (4, 2) and b["ids"].dtype == np.int64
    assert b["x"].shape == (4, 4) and b["x"].dtype == np.float32
    assert b["label"].shape == (4, 1)


def test_threaded_load_matches_serial(tmp_path):
    paths = _write_files(tmp_path, n_files=4)
    serial = InMemoryDataset()
    serial.set_filelist(paths)
    serial.set_feed(MultiSlotDataFeed(_SLOTS))
    serial.load_into_memory()
    threaded = InMemoryDataset()
    threaded.set_filelist(paths)
    threaded.set_thread(4)
    threaded.set_feed(MultiSlotDataFeed(_SLOTS))
    threaded.load_into_memory()
    key = lambda r: tuple(r["ids"])  # noqa: E731
    a = sorted((tuple(r["x"]) for r in serial._records))
    b = sorted((tuple(r["x"]) for r in threaded._records))
    assert a == b


def test_queue_dataset_streams(tmp_path):
    paths = _write_files(tmp_path)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(paths)
    ds.set_batch_size(8)
    ds.set_feed(MultiSlotDataFeed(_SLOTS))
    batches = list(ds)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (8, 4)


def test_local_shuffle_deterministic(tmp_path):
    paths = _write_files(tmp_path)
    a = InMemoryDataset()
    a.set_filelist(paths)
    a.set_feed(MultiSlotDataFeed(_SLOTS))
    a.load_into_memory()
    before = [tuple(r["ids"]) for r in a._records]
    a.local_shuffle(seed=3)
    after = [tuple(r["ids"]) for r in a._records]
    assert before != after and sorted(before) == sorted(after)


def test_multitrainer_hogwild_covers_all_batches(tmp_path):
    paths = _write_files(tmp_path, n_files=4, lines_per_file=8)
    ds = InMemoryDataset()
    ds.set_filelist(paths)
    ds.set_batch_size(4)
    ds.set_feed(MultiSlotDataFeed(_SLOTS))
    seen = []
    import threading

    lock = threading.Lock()

    def step(batch):
        with lock:
            seen.append(batch["x"].shape[0])
        return batch["x"].sum()

    trainer = MultiTrainer(thread_num=3)
    metrics = trainer.train(ds, step)
    assert len(seen) == 8  # 32 records / bs 4
    assert len(metrics) == 8


def test_executor_train_from_dataset(tmp_path):
    """fit-a-line from text files through the static Program path
    (ref book/test_fit_a_line + RunFromDataset)."""
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype(np.float32)
    paths = []
    for f in range(2):
        p = tmp_path / f"lin-{f}.txt"
        rows = []
        for _ in range(64):
            x = rng.randn(4).astype(np.float32)
            y = float(x @ w[:, 0])
            rows.append("4 " + " ".join(f"{v:.6f}" for v in x)
                        + f" 1 {y:.6f}")
        p.write_text("\n".join(rows) + "\n")
        paths.append(str(p))

    paddle.enable_static()
    main, startup = static.Program(), static.Program()
    try:
        with static.program_guard(main, startup):
            x = static.data("x", [8, 4], "float32")
            label = static.data("label", [8, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean(
                paddle.nn.functional.square_error_cost(pred, label))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)

            ds = InMemoryDataset()
            ds.set_filelist(paths)
            ds.set_batch_size(8)
            ds.set_feed(MultiSlotDataFeed(
                [("x", "float", 4), ("label", "float", 1)]))
            ds.load_into_memory()

            exe = static.Executor()
            exe.run(startup)
            losses_1 = exe.train_from_dataset(main, ds,
                                              fetch_list=[loss])
            losses_2 = exe.train_from_dataset(main, ds,
                                              fetch_list=[loss])
            first = float(losses_1[0][0])
            last = float(losses_2[-1][0])
            assert last < first * 0.1, (first, last)
    finally:
        paddle.disable_static()
