"""Per-op micro-benchmark + cross-round regression gate.

Ref parity: paddle/fluid/operators/benchmark/op_tester.cc +
tools/test_op_benchmark.sh + tools/check_op_benchmark_result.py — the
reference times each op kernel and fails CI when a PR regresses one.
Here the hot ops run under the same differenced-scan method as bench.py
(one dispatch, data-dependent chain, paired differencing to cancel
tunnel overhead).

Usage:
    python bench_ops.py                   # run, print one JSON line/op
    python bench_ops.py --save            # also update the baseline
    python bench_ops.py --check           # fail (exit 1) on >35% regress
    python bench_ops.py --macro [--save|--check]
        # model-level gates instead of the micro set: flash-attention
        # fwd+bwd at seq 512/1024/2048 (the quoted flash-vs-XLA wins)
        # and the seq-8192 longctx GPT train step — so those numbers
        # cannot silently rot (VERDICT r3 item 8)
Baseline: bench_ops_baseline.json (checked in; --save merges the keys it
ran, so micro and macro runs maintain disjoint halves of one file).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_ops_baseline.json")
REGRESS_TOLERANCE = 1.35  # >35% slower than baseline fails the gate
ABS_NOISE_MS = 0.05       # tunnel timing noise floor for tiny ops


def _specs():
    """op name -> (fn(x) -> array, example input). Shapes mirror the
    ERNIE-base ladder (batch 32, seq 512, hidden 768)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.op_registry import lookup

    f = lambda name: lookup(name).fn  # noqa: E731
    rng = np.random.RandomState(0)
    h = 768
    x_bsh = jnp.asarray(rng.randn(32, 512, h), jnp.bfloat16)
    w_hh = jnp.asarray(rng.randn(h, h), jnp.bfloat16)
    w_ffn = jnp.asarray(rng.randn(h, 4 * h), jnp.bfloat16)
    img = jnp.asarray(rng.randn(32, 64, 56, 56), jnp.bfloat16)
    kconv = jnp.asarray(rng.randn(64, 64, 3, 3), jnp.bfloat16)
    qkv = jnp.asarray(rng.randn(32, 12, 512, 64), jnp.bfloat16)
    ids = jnp.asarray(rng.randint(0, 18000, (32, 512)), jnp.int32)
    emb = jnp.asarray(rng.randn(18000, h), jnp.bfloat16)
    gamma = jnp.ones((h,), jnp.float32)
    key = jax.random.PRNGKey(0)

    return {
        "matmul_qkv": (lambda x: jnp.matmul(x, w_hh), x_bsh),
        "matmul_ffn": (lambda x: jnp.matmul(x, w_ffn), x_bsh),
        "flash_attention": (
            lambda x: f("flash_attention")(x, qkv, qkv, is_causal=False),
            qkv),
        "softmax": (lambda x: f("softmax")(x, axis=-1), x_bsh),
        "layer_norm": (
            lambda x: f("layer_norm")(x, gamma, jnp.zeros_like(gamma),
                                      begin_norm_axis=2), x_bsh),
        "gelu": (lambda x: f("gelu")(x), x_bsh),
        "dropout": (
            lambda x: f("dropout")(x, key, p=0.1, training=True), x_bsh),
        # carry the TABLE (float) so the scan chain stays data-dependent
        "lookup_table_v2": (lambda e: f("lookup_table_v2")(ids, e), emb),
        # the fused LM-head loss at bench shape: hidden states against
        # the full 18000-vocab tied table, no [N, V] logits materialised
        "fused_linear_cross_entropy": (
            lambda e: f("fused_linear_cross_entropy")(
                x_bsh.reshape(-1, h), e, ids.reshape(-1)), emb),
        "conv2d": (lambda x: f("conv2d")(x, kconv, stride=1, padding=1),
                   img),
        "pool2d": (lambda x: f("pool2d")(x, ksize=2, stride=2,
                                         pooling_type="max"), img),
        "reduce_sum": (lambda x: f("reduce_sum")(x, axis=-1), x_bsh),
        "transpose": (lambda x: f("transpose")(x, perm=(0, 2, 1)), x_bsh),
        "elementwise_add": (lambda x: f("elementwise_add")(x, x), x_bsh),
        "cumsum": (lambda x: f("cumsum")(x, axis=-1), x_bsh),
        "softmax_with_cross_entropy": (
            lambda x: f("softmax_with_cross_entropy")(
                x.reshape(-1, h).astype(jnp.float32),
                ids.reshape(-1) % h)[0], x_bsh),
    }


def _time_op(fn, x, iters=40):
    """Differenced-scan ms/op: chain iterations through a data
    dependency, time N and 3N inside one jit each, min of paired
    diffs. Ops faster than ~50us re-run with 8x the iterations so the
    marginal cost clears the tunnel's timing noise."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def once(v):
        out = fn(v)
        if isinstance(out, tuple):
            out = out[0]
        return out._value if hasattr(out, "_value") else out

    def make(n):
        @jax.jit
        def run(v):
            def body(carry, _):
                out = once(carry)
                # fold output magnitude back into the carry to chain
                delta = jnp.real(out).astype(jnp.float32).mean() * 1e-6
                return (carry + delta.astype(carry.dtype)
                        if jnp.issubdtype(carry.dtype, jnp.floating)
                        else carry), delta
            carry, deltas = lax.scan(body, v, None, length=n)
            return deltas[-1]
        return run

    def measure(n):
        r1, r2 = make(n), make(3 * n)
        for r in (r1, r2):
            float(np.asarray(r(x)))
        diffs = []
        for _ in range(4):
            t0 = time.perf_counter()
            float(np.asarray(r1(x)))
            t1 = time.perf_counter()
            float(np.asarray(r2(x)))
            t2 = time.perf_counter()
            diffs.append((t2 - t1) - (t1 - t0))
        return max(min(diffs) / (2 * n) * 1e3, 0.0)

    ms = measure(iters)
    if ms < 0.05:
        ms = measure(8 * iters)
    return ms


def _macro_specs():
    """Model-level gates timed like the micro ops: flash attention
    fwd+bwd (default dispatch — the Pallas kernel on TPU at these seq
    lengths) at the quoted ladder sizes, b=32 h=12 d=64 causal."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.op_registry import lookup

    flash = lookup("flash_attention").fn
    rng = np.random.RandomState(0)
    specs = {}
    for s, iters in ((512, 16), (1024, 8), (2048, 4)):
        k = jnp.asarray(rng.randn(32, 12, s, 64), jnp.bfloat16)
        v = jnp.asarray(rng.randn(32, 12, s, 64), jnp.bfloat16)
        q = jnp.asarray(rng.randn(32, 12, s, 64), jnp.bfloat16)

        def fwd_bwd(x, k=k, v=v):
            return jax.grad(lambda a: jnp.sum(
                flash(a, k, v, is_causal=True).astype(jnp.float32)))(x)

        specs[f"flash_fwd_bwd_s{s}"] = (fwd_bwd, q, iters)
    return specs


def _run_longctx():
    """The seq-8192 one-chip GPT train step, via its canonical
    implementation (bench_attrib.py longctx) in a subprocess; returns
    step_ms or raises."""
    import subprocess

    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_attrib.py"), "longctx"],
        capture_output=True, text=True, timeout=1800)
    for line in reversed(out.stdout.splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("variant") == "longctx":
            return float(rec["step_ms"])
    raise RuntimeError(f"longctx bench produced no result: "
                       f"{out.stdout[-500:]}\n{out.stderr[-500:]}")


def main(argv):
    save = "--save" in argv
    check = "--check" in argv
    macro = "--macro" in argv
    import jax

    dev = jax.devices()[0]
    results = {}
    if macro:
        for name, (fn, x, iters) in _macro_specs().items():
            try:
                ms = _time_op(fn, x, iters=iters)
            except Exception as e:  # noqa: BLE001 — report, continue
                print(json.dumps({"op": name, "error": repr(e)[:200]}))
                continue
            results[name] = round(ms, 4)
            print(json.dumps({"op": name, "ms": results[name],
                              "device": getattr(dev, "device_kind",
                                                dev.platform)}))
        try:
            results["longctx_gpt_s8192_step"] = round(_run_longctx(), 2)
            print(json.dumps({"op": "longctx_gpt_s8192_step",
                              "ms": results["longctx_gpt_s8192_step"]}))
        except Exception as e:  # noqa: BLE001 — report, continue
            print(json.dumps({"op": "longctx_gpt_s8192_step",
                              "error": repr(e)[:200]}))
        return _finish(results, dev, save, check)
    for name, (fn, x) in _specs().items():
        try:
            ms = _time_op(fn, x)
        except Exception:  # noqa: BLE001 — tunnel flake: one retry
            try:
                ms = _time_op(fn, x)
            except Exception as e:  # noqa: BLE001 — report, continue
                print(json.dumps({"op": name, "error": repr(e)[:200]}))
                continue
        results[name] = round(ms, 4)
        print(json.dumps({"op": name, "ms": results[name],
                          "device": getattr(dev, "device_kind",
                                            dev.platform)}))
    return _finish(results, dev, save, check)


def _finish(results, dev, save, check):
    kind = getattr(dev, "device_kind", dev.platform)
    if save:
        base = {"device": kind, "ops": {}}
        if os.path.exists(BASELINE_PATH):
            base = json.load(open(BASELINE_PATH))
        if base.get("device") != kind:
            # numbers from another device are meaningless to merge with
            base = {"device": kind, "ops": {}}
        # merge: micro and macro runs each maintain their own keys
        base.setdefault("ops", {}).update(results)
        with open(BASELINE_PATH, "w") as f:
            json.dump(base, f, indent=1, sort_keys=True)
        print(json.dumps({"saved": BASELINE_PATH}))
        return 0
    if check:
        if not os.path.exists(BASELINE_PATH):
            print(json.dumps({"check": "no baseline"}))
            return 1
        base = json.load(open(BASELINE_PATH))
        if base.get("device") != kind:
            print(json.dumps({"check": "skipped",
                              "reason": "different device"}))
            return 0
        bad = []
        for op, ms in results.items():
            ref = base["ops"].get(op)
            if ref and ms > ref * REGRESS_TOLERANCE \
                    and ms - ref > ABS_NOISE_MS:
                bad.append({"op": op, "ms": ms, "baseline_ms": ref})
        print(json.dumps({"check": "fail" if bad else "ok",
                          "regressions": bad}))
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
